"""The signed contribution ledger: receipt-backed swarm accounting.

Every sample count in the progress tracker is self-reported; the ledger
makes contribution accounting *checkable* with two signed DHT record
families riding the same validator chain as the checkpoint catalog
(collaborative/metrics.py ``make_validators``):

- ``{prefix}_contribution_ledger`` — one ``ContributionClaim`` per peer
  (subkey = the peer's RSA owner tag, so the record is signature-bound):
  cumulative samples accumulated, rounds completed, wall-seconds trained,
  and bytes served as a checkpoint/state provider. Claims are what a peer
  SAYS it did.
- ``{prefix}_round_receipts`` — one ``RoundReceipt`` per peer, refreshed
  at each averaging-round finalization: the last round's member set and
  declared weights (signed over the matchmaking envelope identities the
  signer already verified at join time) plus a bounded cumulative
  ``witness`` table — how many declared samples this signer has watched
  each group-mate bring across all rounds so far. Receipts are what the
  swarm SAW a peer do.

The coordinator folds one against the other (``fold_ledger``): a peer's
credited samples are ``min(claimed, receipt-supported x slack)``, where
receipt-supported is the largest witness total any OTHER peer countersigns
for it — so a peer cannot be credited for samples no group-mate ever saw,
and an inflated claim surfaces as a named per-peer ``discrepancy``. A peer
whose claim record was lost but whose work was witnessed is credited its
witnessed total (receipts are evidence, not just a cap). The fold is
deterministic for fixed inputs — replaying a dumped ledger JSONL must
reproduce it bit-identically.

Both record families are cumulative by construction: an RSA-validated
subkey must be exactly the owner tag (dht/validation.py), so each peer has
ONE slot per family and every store is a last-write-wins refresh — there
is no per-round record to garbage-collect.

Identity binding: the ``peer``/``signer`` field inside a record is only
trusted when it matches the identity its storage slot speaks for
(``subkey_owner_id``): an RSA owner-tag subkey binds to the key digest
gated matchmaking already uses as the peer id
(core/auth.peer_id_from_public_key), a raw-bytes subkey binds to itself.
``parse_claims``/``parse_receipts`` DROP any record that fails the
binding, so a peer cannot publish, under its own valid slot, a claim
naming a victim or a receipt whose fabricated ``signer`` launders a
witness table crediting itself.
"""
from __future__ import annotations

import math
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

from pydantic import BaseModel, StrictInt, StrictStr, model_validator

from dedloc_tpu.core.auth import peer_id_from_public_key
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.validation import OWNER_PREFIX
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# witness-table bound: a receipt must stay a small DHT record even after a
# peer has averaged with thousands of partners — keep the top entries by
# witnessed samples (the tail it drops is exactly the tail that cannot
# support a large claim anyway)
MAX_WITNESS = 512

# default over-claim slack: claims run ahead of receipts by up to one
# publication period (samples accumulated since the last receipted round),
# so the fold tolerates a bounded multiplicative lead before it flags
DEFAULT_SLACK = 1.25

LEGS = ("flat", "gossip", "clique")


_STEP_RE = re.compile(r"step[_-]?(\d+)")


def parse_round_step(round_id: str) -> int:
    """Optimizer step encoded in a round id (the collaborative optimizer
    keys rounds ``step{N}``); -1 when the id carries none (bare averager
    or simulator rounds)."""
    m = _STEP_RE.search(str(round_id))
    return int(m.group(1)) if m else -1


def ledger_key(prefix: str) -> str:
    return f"{prefix}_contribution_ledger"


def receipts_key(prefix: str) -> str:
    return f"{prefix}_round_receipts"


def _finite(x: float) -> bool:
    return isinstance(x, (int, float)) and math.isfinite(float(x))


class ContributionClaim(BaseModel):
    """One peer's cumulative self-report (validated at every storing node
    by the DHT's SchemaValidator chain, like the checkpoint catalog)."""

    peer: StrictStr  # averager peer_id, hex — joins claims to receipts
    samples: StrictInt  # cumulative samples accumulated
    rounds: StrictInt  # cumulative averaging rounds completed
    train_seconds: float  # wall-seconds since the optimizer came up
    bytes_served: StrictInt  # ckpt.shard_bytes_served + state.served_bytes
    # inference requests served from the expert-serving plane
    # (serving/host.py) — optional with a 0 default so pre-serving claim
    # records keep parsing unchanged
    requests_served: StrictInt = 0
    time: float  # publication stamp (DHT clock)

    @model_validator(mode="after")
    def _check(self) -> "ContributionClaim":
        if not self.peer or len(self.peer) > 128:
            raise ValueError(f"bad peer id {self.peer!r}")
        if (
            self.samples < 0 or self.rounds < 0
            or self.bytes_served < 0 or self.requests_served < 0
        ):
            raise ValueError("claim totals must be non-negative")
        if not _finite(self.train_seconds) or self.train_seconds < 0:
            raise ValueError(f"bad train_seconds {self.train_seconds!r}")
        if not _finite(self.time):
            raise ValueError(f"bad time {self.time!r}")
        return self


class WitnessEntry(BaseModel):
    """What one signer has cumulatively watched one group-mate declare."""

    samples: float  # sum of the mate's declared weights across rounds
    rounds: StrictInt  # rounds the signer shared a group with the mate

    @model_validator(mode="after")
    def _check(self) -> "WitnessEntry":
        if not _finite(self.samples) or self.samples < 0:
            raise ValueError(f"bad witnessed samples {self.samples!r}")
        if self.rounds < 0:
            raise ValueError(f"negative witnessed rounds {self.rounds}")
        return self


class RoundReceipt(BaseModel):
    """One peer's countersignature over its last finalized round plus its
    cumulative witness table. ``members``/``weights`` are aligned and cover
    the matchmaking identities the signer verified (gated runs: each
    member's record arrived in an authority-signed envelope bound to that
    identity); delegates in hierarchical mode countersign their clique's
    SUM leg (``leg="clique"``)."""

    signer: StrictStr  # hex peer id; parse_receipts drops any record
    # whose signer does not match its storage slot (subkey_owner_id)
    round_id: StrictStr
    step: StrictInt  # optimizer step parsed from the round id (-1 unknown)
    leg: StrictStr  # flat | gossip | clique
    members: List[StrictStr]  # hex ids, strictly sorted + unique
    weights: List[float]  # declared weights, aligned with ``members``
    witness: Dict[str, WitnessEntry]
    time: float

    @model_validator(mode="after")
    def _check(self) -> "RoundReceipt":
        if self.leg not in LEGS:
            raise ValueError(f"unknown receipt leg {self.leg!r}")
        if self.step < -1:
            raise ValueError(f"bad step {self.step}")
        if len(self.members) < 2:
            raise ValueError("a receipt needs >= 2 members")
        if len(self.members) > 4096:
            raise ValueError(f"absurd member count {len(self.members)}")
        if self.members != sorted(set(self.members)):
            raise ValueError("members must be strictly sorted and unique")
        if len(self.weights) != len(self.members):
            raise ValueError("weights must align with members")
        if self.signer not in self.members:
            raise ValueError("signer must be a group member")
        for w in self.weights:
            if not _finite(w) or w < 0:
                raise ValueError(f"bad declared weight {w!r}")
        if len(self.witness) > MAX_WITNESS:
            raise ValueError(
                f"witness table over bound ({len(self.witness)} > "
                f"{MAX_WITNESS})"
            )
        if not _finite(self.time):
            raise ValueError(f"bad time {self.time!r}")
        return self


# ------------------------------------------------------------ publication


def publish_claim(dht, prefix: str, subkey: bytes,
                  claim: ContributionClaim,
                  expiration: float = 300.0) -> None:
    """Store this peer's claim record (non-blocking, like the catalog
    announcement it rides next to)."""
    dht.store(
        ledger_key(prefix),
        claim.model_dump(),
        get_dht_time() + expiration,
        subkey=subkey,
        return_future=True,
    )


def publish_receipt(dht, prefix: str, subkey: bytes,
                    receipt: RoundReceipt,
                    expiration: float = 300.0) -> None:
    dht.store(
        receipts_key(prefix),
        receipt.model_dump(),
        get_dht_time() + expiration,
        subkey=subkey,
        return_future=True,
    )


def subkey_owner_id(subkey) -> Optional[str]:
    """The ONE peer id a ledger record stored under ``subkey`` may speak
    for. An RSA owner tag (dht/validation.py: the only subkey shape whose
    writes are signature-checked at storing nodes) binds cryptographically
    to the key-digest id gated matchmaking already enforces as the peer
    identity (core/auth.peer_id_from_public_key). A raw-bytes subkey binds
    structurally to itself — the open-swarm trust model, where node ids
    are free and unsigned slots are writable by anyone. None = unbindable
    shape; callers must drop the record."""
    if isinstance(subkey, str):
        subkey = subkey.encode()
    if not isinstance(subkey, (bytes, bytearray)):
        return None
    subkey = bytes(subkey)
    if subkey.startswith(OWNER_PREFIX):
        try:
            return peer_id_from_public_key(subkey[len(OWNER_PREFIX):]).hex()
        except Exception:  # noqa: BLE001 — undigestible tag
            return None
    return subkey.hex()


def parse_claims(entry_items) -> List[ContributionClaim]:
    """THE one parsing path for claim records: drop anything that fails
    the schema (defense in depth — a storing node that predates the schema
    may have accepted garbage) and anything whose ``peer`` does not match
    the identity its subkey speaks for (``subkey_owner_id``) — a peer
    cannot publish a claim naming somebody else under its own slot.
    ``entry_items`` iterates (subkey, unpacked claim dict)."""
    out: List[ContributionClaim] = []
    for sk, value in entry_items:
        try:
            claim = ContributionClaim.model_validate(value)
        except Exception as e:  # noqa: BLE001 — malformed claim
            logger.debug(f"dropping malformed claim record: {e!r}")
            continue
        owner = subkey_owner_id(sk)
        if owner != claim.peer:
            logger.debug(
                f"dropping claim for {claim.peer!r}: its slot speaks for "
                f"{owner!r}"
            )
            continue
        out.append(claim)
    return out


def parse_receipts(entry_items) -> List[RoundReceipt]:
    """Same hardening for receipts: a record whose ``signer`` is not the
    identity its subkey speaks for is DROPPED before the fold ever sees
    its witness table — otherwise a peer could countersign its own work
    under a fabricated signer id and bypass the self-witness skip."""
    out: List[RoundReceipt] = []
    for sk, value in entry_items:
        try:
            receipt = RoundReceipt.model_validate(value)
        except Exception as e:  # noqa: BLE001 — malformed receipt
            logger.debug(f"dropping malformed receipt record: {e!r}")
            continue
        owner = subkey_owner_id(sk)
        if owner != receipt.signer:
            logger.debug(
                f"dropping receipt signed {receipt.signer!r}: its slot "
                f"speaks for {owner!r}"
            )
            continue
        out.append(receipt)
    return out


# --------------------------------------------------------------- witness


def update_witness(witness: Dict[str, Dict[str, float]],
                   mates: Iterable[Tuple[str, float]]) -> None:
    """Fold one finalized round's group-mates into a signer's cumulative
    witness table in place. ``mates`` iterates (peer_hex, declared_weight)
    for every OTHER member of the group. Bounded to ``MAX_WITNESS``
    entries by witnessed samples — the droppable tail is the set of peers
    whose totals could not support a meaningful claim anyway."""
    for peer, weight in mates:
        entry = witness.setdefault(peer, {"samples": 0.0, "rounds": 0})
        entry["samples"] = float(entry["samples"]) + max(0.0, float(weight))
        entry["rounds"] = int(entry["rounds"]) + 1
    if len(witness) > MAX_WITNESS:
        keep = sorted(
            witness.items(),
            key=lambda kv: (-float(kv[1]["samples"]), kv[0]),
        )[:MAX_WITNESS]
        witness.clear()
        witness.update(keep)


def receipt_from_group(signer_hex: str, round_id: str, step: int, leg: str,
                       member_weights: List[Tuple[str, float]],
                       witness: Dict[str, Dict[str, float]],
                       now: Optional[float] = None) -> RoundReceipt:
    """Build the signer's refreshed receipt after updating its witness
    table with the round just finalized. ``member_weights`` lists every
    group member (including the signer) as (peer_hex, declared_weight)."""
    update_witness(
        witness,
        [(p, w) for p, w in member_weights if p != signer_hex],
    )
    ordered = sorted({p: float(w) for p, w in member_weights}.items())
    return RoundReceipt(
        signer=signer_hex,
        round_id=str(round_id),
        step=int(step),
        leg=str(leg),
        members=[p for p, _w in ordered],
        weights=[round(w, 6) for _p, w in ordered],
        witness={
            p: WitnessEntry(
                samples=round(float(e["samples"]), 6),
                rounds=int(e["rounds"]),
            )
            for p, e in sorted(witness.items())
        },
        time=float(now if now is not None else get_dht_time()),
    )


# ------------------------------------------------------------------ fold


def fold_ledger(prev: Optional[Dict[str, Any]],
                claims: List[ContributionClaim],
                receipts: List[RoundReceipt],
                slack: float = DEFAULT_SLACK,
                now: Optional[float] = None) -> Dict[str, Any]:
    """One coordinator fold of claims against receipts into the durable
    cumulative ledger state. Restart-safe last-state-wins: both record
    families are cumulative, so a peer present in the current DHT view
    fully supersedes its ``prev`` entry, and a peer whose records expired
    keeps its ``prev`` entry (with a coverage note) instead of vanishing.

    Receipt support is MONOTONE for peers still in the view: receipts
    expire (~300s) long before claims stop refreshing, so a long-running
    peer whose former group-mates left would otherwise flip to
    "unwitnessed" and lose all credit. The ``prev`` fold's
    ``supported_samples``/``supported_rounds`` floor the current support
    (both families are cumulative, so the max is sound); a peer covered
    only by that carried floor is marked ``coverage="carried"`` — still
    capped, never falsely flagged.

    Deterministic for fixed inputs: peers fold in sorted order and floats
    are rounded, so replaying a dumped ledger JSONL reproduces the state
    bit-identically (the acceptance bar)."""
    t = float(now if now is not None else get_dht_time())
    slack = float(slack)
    # receipt-supported totals: the LARGEST witness any other signer
    # countersigns (witness tables are cumulative maxima, not addable —
    # summing two signers' tables would double-count shared rounds)
    supported: Dict[str, Dict[str, float]] = {}
    for r in receipts:
        for peer, entry in r.witness.items():
            if peer == r.signer:
                continue  # self-witness is just the claim again
            cur = supported.setdefault(peer, {"samples": 0.0, "rounds": 0})
            cur["samples"] = max(cur["samples"], float(entry.samples))
            cur["rounds"] = max(cur["rounds"], int(entry.rounds))
    have_receipts = bool(receipts)
    prev_peers = dict((prev or {}).get("peers") or {})

    def _floor(peer: str) -> Tuple[float, int]:
        """Receipt support carried from the prev fold (0,0 when the peer
        was never receipt-covered — pre-ledger entries carry None)."""
        old = prev_peers.get(peer)
        if not isinstance(old, dict):
            return 0.0, 0
        s = old.get("supported_samples")
        if not isinstance(s, (int, float)):
            return 0.0, 0
        r = old.get("supported_rounds")
        if not isinstance(r, (int, float)):
            r = old.get("credited_rounds") or 0  # pre-field ledger rows
        return float(s), int(r)

    peers: Dict[str, Dict[str, Any]] = {}
    for claim in sorted(claims, key=lambda c: (c.peer, -c.time)):
        if claim.peer in peers:
            continue  # first (latest) claim per peer wins
        sup = supported.get(claim.peer)
        floor_s, floor_r = _floor(claim.peer)
        eff_s = max(sup["samples"] if sup else 0.0, floor_s)
        eff_r = max(sup["rounds"] if sup else 0, floor_r)
        witnessed = sup is not None or floor_s > 0 or floor_r > 0
        entry: Dict[str, Any] = {
            "peer": claim.peer,
            "claimed_samples": int(claim.samples),
            "claimed_rounds": int(claim.rounds),
            "train_seconds": round(float(claim.train_seconds), 3),
            "bytes_served": int(claim.bytes_served),
            "requests_served": int(claim.requests_served),
            "last_claim_t": round(float(claim.time), 3),
            "discrepancy": None,
        }
        if not have_receipts and not witnessed:
            # pre-ledger swarm: no receipt evidence exists anywhere, now
            # or in any prior fold — credit as claimed, say so
            entry["coverage"] = "pre-ledger"
            entry["supported_samples"] = None
            entry["supported_rounds"] = None
            entry["credited_samples"] = int(claim.samples)
            entry["credited_rounds"] = int(claim.rounds)
        elif not witnessed:
            # receipts exist but nobody (current or prior fold) witnessed
            # this peer: a non-zero claim is unsupported — named, zero
            entry["coverage"] = "unwitnessed"
            entry["supported_samples"] = 0.0
            entry["supported_rounds"] = 0
            entry["credited_samples"] = 0
            entry["credited_rounds"] = 0
            if claim.samples > 0:
                entry["discrepancy"] = {
                    "kind": "unwitnessed",
                    "claimed_samples": int(claim.samples),
                    "supported_samples": 0.0,
                }
        else:
            cap = eff_s * slack
            credited = min(float(claim.samples), cap)
            entry["coverage"] = "receipts" if sup is not None else "carried"
            entry["supported_samples"] = round(eff_s, 3)
            entry["supported_rounds"] = int(eff_r)
            entry["credited_samples"] = int(round(credited))
            entry["credited_rounds"] = min(
                int(claim.rounds), int(eff_r * slack) + 1
            )
            if float(claim.samples) > cap:
                entry["discrepancy"] = {
                    "kind": "overclaim",
                    "claimed_samples": int(claim.samples),
                    "supported_samples": round(eff_s, 3),
                    "ratio": round(
                        float(claim.samples) / max(eff_s, 1e-9),
                        3,
                    ),
                }
        peers[claim.peer] = entry
    # witnessed-but-claimless peers: their claim record was lost or they
    # never published one, but group-mates countersigned their work —
    # credit the witnessed total (receipts are evidence, not just a cap)
    for peer in sorted(supported):
        if peer in peers:
            continue
        sup = supported[peer]
        if sup["samples"] <= 0 and sup["rounds"] <= 0:
            continue
        floor_s, floor_r = _floor(peer)
        eff_s = max(sup["samples"], floor_s)
        eff_r = max(int(sup["rounds"]), floor_r)
        peers[peer] = {
            "peer": peer,
            "claimed_samples": 0,
            "claimed_rounds": 0,
            "train_seconds": 0.0,
            "bytes_served": 0,
            "requests_served": 0,
            "last_claim_t": None,
            "coverage": "receipts-only",
            "supported_samples": round(eff_s, 3),
            "supported_rounds": int(eff_r),
            "credited_samples": int(round(eff_s)),
            "credited_rounds": int(eff_r),
            "discrepancy": None,
        }
    # restart-safe carry-over: peers whose records expired keep their last
    # folded state, flagged stale so the view can say why
    for peer, old in sorted(((prev or {}).get("peers") or {}).items()):
        if peer not in peers and isinstance(old, dict):
            kept = dict(old)
            kept["coverage"] = "stale"
            peers[peer] = kept

    ordered = {p: peers[p] for p in sorted(peers)}
    total = sum(int(e.get("credited_samples") or 0) for e in ordered.values())
    return {
        "t": round(t, 3),
        "slack": round(slack, 4),
        "claims": len(claims),
        "receipt_signers": len({r.signer for r in receipts}),
        "total_credited_samples": total,
        "discrepancies": sum(
            1 for e in ordered.values() if e.get("discrepancy")
        ),
        "peers": ordered,
    }


def leaderboard(ledger: Dict[str, Any]) -> List[Dict[str, Any]]:
    """The ledger state as ranked leaderboard rows — THE one ranking both
    ``runlog_summary --contributions`` and ``swarm_watch --brief`` render,
    so the two surfaces can never disagree about who is on top."""
    entries = list((ledger.get("peers") or {}).values())
    total = float(
        sum(int(e.get("credited_samples") or 0) for e in entries)
    )
    rows: List[Dict[str, Any]] = []
    for e in sorted(
        entries,
        key=lambda e: (
            -int(e.get("credited_samples") or 0),
            -int(e.get("bytes_served") or 0),
            -int(e.get("requests_served") or 0),
            str(e.get("peer")),
        ),
    ):
        credited = int(e.get("credited_samples") or 0)
        rows.append({
            "peer": e.get("peer"),
            "credited_samples": credited,
            "claimed_samples": int(e.get("claimed_samples") or 0),
            "credited_rounds": int(e.get("credited_rounds") or 0),
            "bytes_served": int(e.get("bytes_served") or 0),
            "requests_served": int(e.get("requests_served") or 0),
            "share": round(credited / total, 4) if total > 0 else 0.0,
            "coverage": e.get("coverage"),
            "discrepancy": e.get("discrepancy"),
        })
    return rows
