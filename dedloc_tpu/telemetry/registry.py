"""Process-local swarm telemetry: counters/gauges/histograms + span tracing.

DeDLOC's operational reality is a fleet of unreliable volunteer peers — the
operator's only lever is knowing WHICH peer is stalling a round. The
reference leans on hivemind's logs plus a wandb dashboard; the step-phase
half lives in ``utils/perf.py`` (vissl PerfStats capability). This module is
the collaborative-machinery half: structured counters and span traces on the
hot seams (DHT RPCs, matchmaking, allreduce rounds, state-sync retries,
ramp/gate decisions, injected faults), written to a per-peer JSONL event log
and periodically snapshotted onto the signed DHT metrics bus
(``collaborative/metrics.py``) so the coordinator can aggregate swarm health
(``telemetry/health.py``).

Design rules, mirroring ``testing/faults.py``:

- **Zero overhead when disabled.** Instrumented code checks the module-level
  ``_active`` attribute (one load + identity test) before touching anything;
  production with telemetry off pays exactly that. Nothing here imports jax.
- **Scoped or global.** Production runs one peer per process, so the roles
  install ONE process-global registry (``install``/``configure``). In-process
  multi-peer tests pass a per-peer ``Telemetry`` instance into the components
  (averager/optimizer/matchmaking/protocol accept ``telemetry=``) so events
  and counters attribute to the right simulated peer; components fall back to
  the global registry when no instance was given (``resolve``).
- **FakeClock-compatible.** Timestamps are ``get_dht_time()`` (scenario time:
  deterministic under ``testing.faults.FakeClock``); span durations use a
  monotonic clock that also advances with the fake-clock offset, so fault
  scenarios replay to deterministic traces and production durations never go
  backwards on an NTP step.

Event-log schema (one JSON object per line; see docs/observability.md):

    {"t": <dht time>, "peer": "<label>", "event": "<name>",
     "dur_s": <float, spans only>, ...site-specific attributes}
"""
from __future__ import annotations

import contextvars
import hashlib
import json
import math
import threading
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional, Tuple

from dedloc_tpu.core import timeutils
from dedloc_tpu.core.timeutils import get_dht_time


def monotonic_clock() -> float:
    """Monotonic duration clock that also honours the FakeClock offset and
    a simulator-installed virtual time source: ``FakeClock.advance(n)``
    moves it forward by ``n`` exactly, so scripted fault scenarios produce
    deterministic span durations, while production (offset 0, no source)
    gets plain ``time.monotonic``. Alias of ``timeutils.monotonic`` — kept
    as the registry's public name for clock injection."""
    return timeutils.monotonic()


# ---------------------------------------------------------------------------
# Cross-peer trace context (docs/observability.md "trace propagation").
#
# A trace context is ``(trace_id, span_id, peer_label, remote)``: the trace a
# region belongs to, the span that is its parent, whose registry opened that
# span, and whether the parent lives on ANOTHER peer (adopted off the RPC
# framing's compact ``tc`` field). Spans push themselves onto the contextvar
# for their duration, so nested spans — and RPC requests issued inside them —
# inherit the linkage; server-side dispatch adopts the caller's context
# around the handler, so serve spans record their REMOTE parent and the
# coordinator can stitch per-peer JSONL into one causal round trace.
#
# The contextvar is per-task on the event loop and per-thread elsewhere, so
# concurrent rounds / concurrent handler tasks never cross-link. All of this
# is only ever touched behind a ``tele is not None`` check: telemetry off
# pays nothing and the wire framing carries zero extra bytes.
# ---------------------------------------------------------------------------

_TRACE: contextvars.ContextVar[Optional[Tuple[str, str, str, bool]]] = (
    contextvars.ContextVar("dedloc_trace", default=None)
)


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def trace_id_for(seed: str) -> str:
    """Deterministic trace id from a swarm-unique seed (the round_id): every
    peer of a round derives the SAME trace id without any wire handshake, so
    their spans stitch even when a hop's context never propagated (dead
    leader, dropped frame)."""
    return hashlib.sha1(seed.encode()).hexdigest()[:16]


def current_trace() -> Optional[Tuple[str, str, str, bool]]:
    """(trace_id, span_id, peer, remote) of the innermost live span, or
    None. ``RPCClient.call`` reads this to build the frame's ``tc`` field."""
    return _TRACE.get()


@contextmanager
def adopt_trace(tc) -> Iterator[None]:
    """Adopt a remote caller's trace context (the ``tc`` list off an RPC
    request frame: ``[trace_id, parent_span_id, caller_peer]``) for the
    duration of the handler — spans opened inside record the remote parent.
    Malformed ``tc`` values are ignored: a hostile or legacy peer must not
    be able to crash the dispatch path."""
    try:
        trace_id, parent_span, caller = (
            str(tc[0]), str(tc[1]), str(tc[2]) if len(tc) > 2 else "",
        )
    except (TypeError, IndexError, KeyError):
        yield
        return
    token = _TRACE.set((trace_id, parent_span, caller, True))
    try:
        yield
    finally:
        _TRACE.reset(token)


class Counter:
    """Monotonically-increasing float (events, bytes, failures). ``lock``
    is the owning registry's: ``+=`` is a non-atomic load/add/store in
    CPython and counters are hit from the trainer thread AND DHT loop
    threads concurrently — unlocked increments silently undercount."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self.value = 0.0
        self._lock = lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-write-wins scalar (queue depths, weight scales)."""

    __slots__ = ("value", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self.value = 0.0
        self._lock = lock

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)


class Histogram:
    """Online duration/size stats: count/total/min/max + recent window
    (the PerfMetric shape, utils/perf.py, minus the jax blocking)."""

    WINDOW = 64

    __slots__ = ("count", "total", "min", "max", "_recent", "_lock")

    def __init__(self, lock: threading.Lock) -> None:
        self.count = 0
        self.total = 0.0
        self.min = math.inf
        self.max = 0.0
        self._recent: Deque[float] = deque(maxlen=self.WINDOW)
        self._lock = lock

    def observe(self, v: float) -> None:
        with self._lock:
            self.count += 1
            self.total += v
            self.min = min(self.min, v)
            self.max = max(self.max, v)
            self._recent.append(v)

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def summary(self) -> Dict[str, float]:
        return {
            "count": float(self.count),
            "mean": self.mean,
            "min": 0.0 if not self.count else self.min,
            "max": self.max,
        }


def _jsonable(v: Any) -> Any:
    """Event attributes must serialize: keep scalars, stringify the rest
    (endpoints, peer ids, exceptions) so a fault-context object can never
    crash the telemetry path."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, bytes):
        return v.hex()[:16]
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


class Telemetry:
    """One peer's telemetry registry: named counters/gauges/histograms plus
    a bounded in-memory event trace, optionally mirrored to a JSONL file.

    Thread-safe: metrics are touched from the trainer thread AND the DHT
    event loop; one lock guards registry lookup and every metric mutation
    (orders of magnitude cheaper than the RPCs they instrument), and the
    JSONL mirror has its OWN lock so a slow disk never blocks counters.
    """

    MAX_EVENTS = 4096  # in-memory trace bound; the JSONL file is unbounded

    def __init__(
        self,
        peer: str = "",
        event_log_path: Optional[str] = None,
        clock: Optional[Callable[[], float]] = None,
        link_top_k: int = 8,
        max_events: Optional[int] = None,
    ) -> None:
        self.peer = peer
        self.clock = clock or monotonic_clock
        self.counters: Dict[str, Counter] = {}
        self.gauges: Dict[str, Gauge] = {}
        self.histograms: Dict[str, Histogram] = {}
        # ``max_events`` overrides the in-memory bound: consumers that read
        # events from MEMORY instead of the JSONL sink (the swarm simulator
        # dumps post-run) need room for a whole scenario per peer
        self.events: Deque[dict] = deque(maxlen=max_events or self.MAX_EVENTS)
        # per-link network estimator (telemetry/links.py), created on first
        # observation; ``link_top_k`` bounds how many links ride the metrics
        # bus snapshot (the busiest first)
        self.link_top_k = int(link_top_k)
        self._links = None
        self._lock = threading.Lock()
        # the JSONL mirror gets its OWN lock: a slow disk stalling an event
        # write must not block counter updates on the DHT event loop
        self._log_lock = threading.Lock()
        self._log = (
            open(event_log_path, "a", buffering=1, encoding="utf-8")
            if event_log_path
            else None
        )
        self._last_snapshot_at: Optional[float] = None
        self._last_snapshot: Optional[Dict[str, float]] = None

    # ------------------------------------------------------------- metrics

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self.counters.get(name)
            if c is None:
                c = self.counters[name] = Counter(self._lock)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self.gauges.get(name)
            if g is None:
                g = self.gauges[name] = Gauge(self._lock)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(self._lock)
            return h

    # --------------------------------------------------------------- links

    def links(self):
        """This peer's per-link network estimator (telemetry/links.py),
        created on first use. Instrumented sites must only reach it behind a
        ``tele is not None`` check — disabled telemetry never allocates it."""
        if self._links is None:
            from dedloc_tpu.telemetry.links import LinkTable

            self._links = LinkTable()
        return self._links

    # -------------------------------------------------------------- events

    def event(self, name: str, **attrs: Any) -> dict:
        """Record a point event (and mirror it to the JSONL log). When a
        trace context is live (inside a span, or a handler that adopted a
        remote caller's context) the record gains the linkage fields
        ``trace`` and ``parent`` — explicit attrs of the same name win (the
        span exit path passes its own)."""
        record = {"t": get_dht_time(), "peer": self.peer, "event": name}
        for k, v in attrs.items():
            record[k] = _jsonable(v)
        if "trace" not in record:
            tc = _TRACE.get()
            if tc is not None:
                record["trace"] = tc[0]
                record["parent"] = tc[1]
        self.events.append(record)  # deque.append is atomic under the GIL
        if self._log is not None:
            line = json.dumps(record) + "\n"
            with self._log_lock:
                try:
                    if self._log is not None:
                        self._log.write(line)
                except (OSError, ValueError):
                    # a full disk / closed file must never kill training
                    pass
        return record

    @contextmanager
    def span(
        self, name: str, trace_seed: Optional[str] = None, **attrs: Any
    ) -> Iterator[Dict[str, Any]]:
        """Trace a region: yields a mutable attrs dict the caller can
        annotate with the outcome (``ctx["ok"] = True``); on exit the span
        becomes one event carrying ``dur_s`` and feeds the histogram of the
        same name.

        Linkage: every span gets a fresh ``span`` id and records ``trace``
        and (when nested or remotely called) ``parent``. The trace id is the
        innermost live context's; with none live it derives from
        ``trace_seed`` (deterministic — every peer of a round seeds from the
        same round_id, so their spans stitch without a handshake) or is
        freshly random. A remote parent (adopted off the RPC framing) also
        stamps ``caller`` with the calling peer's label. The span is the
        live context for its duration, so nested spans and outbound RPCs
        inherit it."""
        ctx: Dict[str, Any] = dict(attrs)
        span_id = new_span_id()
        ambient = _TRACE.get()
        if ambient is not None:
            trace_id, parent, caller, remote = ambient
        else:
            trace_id = (
                trace_id_for(trace_seed) if trace_seed else new_span_id()
            )
            parent, caller, remote = None, "", False
        linkage: Dict[str, Any] = {"trace": trace_id, "span": span_id}
        if parent is not None:
            linkage["parent"] = parent
        if remote and caller:
            linkage["caller"] = caller
        token = _TRACE.set((trace_id, span_id, self.peer, False))
        start = self.clock()
        try:
            yield ctx
        finally:
            _TRACE.reset(token)
            # clamped at 0: a span that straddles a FakeClock exit sees the
            # clock retreat by the whole fake offset — a huge negative
            # duration would poison the histogram min/mean forever
            dur = max(0.0, self.clock() - start)
            self.histogram(name).observe(dur)
            # dict-merge (not double-splat): a caller annotating a key that
            # collides with the linkage must override, not TypeError
            self.event(name, dur_s=dur, **{**linkage, **ctx})

    # ----------------------------------------------------------- snapshots

    def snapshot(self) -> Dict[str, float]:
        """Flat {name: float} view of every metric — the payload that rides
        the signed DHT metrics bus (LocalMetrics.telemetry). Histograms
        flatten to ``<name>.count`` / ``<name>.mean`` / ``<name>.max``."""
        with self._lock:
            out: Dict[str, float] = {}
            for name, c in self.counters.items():
                out[name] = c.value
            for name, g in self.gauges.items():
                out[name] = g.value
            for name, h in self.histograms.items():
                if h.count:
                    out[f"{name}.count"] = float(h.count)
                    out[f"{name}.mean"] = h.mean
                    out[f"{name}.max"] = h.max
        if self._links is not None:
            # bounded top-K per-link estimates ride the same flat snapshot
            # ("link.<host:port>.rtt_s" etc, telemetry/links.py) — the
            # coordinator folds them into the swarm topology record
            out.update(self._links.flat(self.link_top_k))
        return out

    def maybe_snapshot(self, period: float) -> Dict[str, float]:
        """Snapshot freshly at most once per ``period`` seconds (the
        metrics-bus throttle); between refreshes the PREVIOUS snapshot is
        returned rather than None — each publish OVERWRITES the peer's DHT
        subkey, so a None tail on the latest record would zero the
        coordinator's swarm-health counters for most aggregation ticks. A
        slightly stale tail beats a missing one."""
        now = self.clock()
        if (
            self._last_snapshot is None
            or self._last_snapshot_at is None
            or now - self._last_snapshot_at >= period
            # clock retreated (FakeClock exited): refresh rather than serve
            # the frozen pre-exit snapshot until real time catches up
            or now < self._last_snapshot_at
        ):
            self._last_snapshot_at = now
            self._last_snapshot = self.snapshot()
            if self._links is not None:
                # mirror the refreshed link estimates into the event log on
                # the same throttle (one link.stats event per tracked link)
                # so ``runlog_summary --topology`` works from JSONL alone
                self._links.emit_events(self)
        return self._last_snapshot

    def close(self) -> None:
        if self._links is not None:
            # final link.stats flush: short runs (tests, one-round repros)
            # may never cross a snapshot period
            self._links.emit_events(self)
        with self._log_lock:
            if self._log is not None:
                self._log.close()
                self._log = None


# ---------------------------------------------------------------------------
# Process-global registry (one peer per process in production). Instrumented
# code checks ``registry._active is not None`` directly — one attribute load,
# the same production fast path as testing/faults.py.
# ---------------------------------------------------------------------------

_active: Optional[Telemetry] = None


def install(telemetry: Telemetry) -> Telemetry:
    global _active
    _active = telemetry
    return telemetry


def uninstall(telemetry: Optional[Telemetry] = None) -> None:
    global _active
    if telemetry is None or _active is telemetry:
        _active = None


def active() -> Optional[Telemetry]:
    return _active


def enabled() -> bool:
    return _active is not None


def resolve(local: Optional[Telemetry]) -> Optional[Telemetry]:
    """Component-scoped registry if one was injected, else the process
    global, else None (disabled)."""
    return local if local is not None else _active


# cheap helpers for free functions that have no component scope (frame I/O,
# fault firing); all no-ops while telemetry is disabled
def inc(name: str, n: float = 1.0) -> None:
    if _active is not None:
        _active.counter(name).inc(n)


def event(name: str, **attrs: Any) -> None:
    if _active is not None:
        _active.event(name, **attrs)


@contextmanager
def null_span() -> Iterator[Dict[str, Any]]:
    """Shared no-op span for disabled telemetry (lets call sites keep one
    ``with`` shape)."""
    yield {}


def span(name: str, telemetry: Optional[Telemetry] = None, **attrs: Any):
    tele = resolve(telemetry)
    return tele.span(name, **attrs) if tele is not None else null_span()
