"""Coordinator-side swarm-health aggregation.

Per-peer telemetry snapshots ride the signed DHT metrics bus
(``LocalMetrics.telemetry``, one RSA-signed subkey per peer — spoof-
resistant, so a peer cannot blame its retries on someone else). The
coordinator folds them into ONE swarm-health record per aggregation tick,
appended to its metrics JSONL next to the throughput aggregate: straggler
attribution, per-peer retry/fault rates, and round-formation latency — the
"why was step N slow" view the reference could only answer by reading every
volunteer's stderr.

Record shape (see docs/observability.md):

    {"current_step": N,
     "peers": [{"peer": "ab12…", "step": N, "behind": 0,
                "rpc_failures": 0.0, "rounds_attempted": 3.0,
                "phases": {"data_wait": 0.01, "fwd_bwd": 0.4, ...},  # mean s
                "dominant_phase": "fwd_bwd", "mfu": 0.57,
                "overlap_efficiency": 0.93, ...}, ...],
     "straggler": "<peer label of the worst offender, or None>",
     "retry_rate": <state-sync retries / attempts, swarm-wide>,
     "round_formation_s": <mean mm.form_group latency across peers>,
     "faults_injected": <total fault events (test harnesses only)>}

The ``phases``/``dominant_phase``/``mfu``/``overlap_*`` fields come from the
step-phase flight recorder (``telemetry/steps.py``); peers on pre-recorder
builds simply lack them — their rows fold unchanged.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# counter names lifted from the instrumented seams; a missing key reads 0.0
# so peers running older builds (no telemetry tail) still aggregate
_PEER_COUNTERS = {
    "rpc_failures": "rpc.client.failures",
    "rpc_calls": "rpc.client.calls",
    # connection-death count: with rpc_calls it gives the per-peer loss
    # rate a telemetry-fitted simulator model (dedloc_tpu/twin) reads
    "conns_lost": "rpc.conns_lost",
    "rounds_attempted": "mm.rounds_attempted",
    "rounds_formed": "mm.rounds_formed",
    "rounds_aborted": "mm.rounds_aborted",
    "join_failures": "mm.join_failures",
    "leader_changes": "mm.leader_changes",
    "state_sync_attempts": "state_sync.attempts",
    "state_sync_retries": "state_sync.retries",
    "state_sync_failures": "state_sync.failures",
    "checksum_failures": "state_sync.checksum_failures",
    "grads_dropped": "opt.grads_dropped",
    "grads_applied": "opt.grads_applied",
    "faults_injected": "faults.applied",
}


def _peer_entry(m, current_step: int) -> Dict:
    t = m.telemetry or {}
    entry: Dict = {
        "peer": m.peer,
        "step": m.step,
        "behind": max(0, current_step - m.step),
        "samples_per_second": m.samples_per_second,
    }
    if m.step_time_ms is not None:
        entry["step_time_ms"] = m.step_time_ms
    for out_key, counter in _PEER_COUNTERS.items():
        entry[out_key] = float(t.get(counter, 0.0))
    form = t.get("mm.form_group.mean")
    if form is not None:
        entry["round_formation_s"] = float(form)
    round_dur = t.get("avg.round.mean")
    if round_dur is not None:
        entry["round_s"] = float(round_dur)
    # step-phase flight recorder (telemetry/steps.py): per-phase mean
    # seconds from the snapshot's ``step.phase.<name>.mean`` histogram keys,
    # plus the dominant phase — the coordinator-side half of "why was step N
    # slow now ends in a PHASE". Absent for pre-recorder peers (no keys).
    phases = {}
    for key, value in t.items():
        if (
            isinstance(key, str)
            and key.startswith("step.phase.")
            and key.endswith(".mean")
        ):
            try:
                phases[key[len("step.phase."):-len(".mean")]] = float(value)
            except (TypeError, ValueError):
                continue
    if phases:
        entry["phases"] = phases
        entry["dominant_phase"] = max(phases, key=phases.get)
    mfu = t.get("step.mfu")
    if mfu is not None:
        entry["mfu"] = float(mfu)
    # mean verified checkpoint-fetch goodput this peer measured against its
    # providers — an uplink-bandwidth signal for the twin fitter that
    # exists even on fleets that never ran a single averaging round
    provider_goodput = t.get("ckpt.provider_goodput.mean")
    if provider_goodput is not None:
        entry["provider_goodput_bps"] = float(provider_goodput)
    # overlap ledger (collaborative optimizer): cumulative hidden/exposed
    # averaging seconds → lifetime overlap efficiency for this peer
    hidden = float(t.get("opt.overlap_hidden_s", 0.0))
    exposed = float(t.get("opt.overlap_exposed_s", 0.0))
    if hidden or exposed:
        entry["overlap_hidden_s"] = hidden
        entry["overlap_exposed_s"] = exposed
        entry["overlap_efficiency"] = hidden / (hidden + exposed)
    return entry


def _peer_links(tail: Dict) -> Dict[str, Dict[str, float]]:
    """Parse a snapshot's flat ``link.<dst>.<field>`` keys (telemetry/links
    LinkTable.flat) back into per-destination records. Tolerant by
    construction: snapshots that predate link telemetry simply have no
    ``link.`` keys and fold to ``{}`` — the peer keeps its ordinary
    per-peer row, it is never dropped from the fold."""
    links: Dict[str, Dict[str, float]] = {}
    for key, value in tail.items():
        if not isinstance(key, str) or not key.startswith("link."):
            continue
        # rsplit once: field names never contain dots, destinations
        # ("10.0.0.1:31337") routinely do
        dst, _, field = key[len("link."):].rpartition(".")
        if not dst or not field:
            continue
        try:
            links.setdefault(dst, {})[field] = float(value)
        except (TypeError, ValueError):
            continue
    return links


def build_topology(records) -> Optional[Dict]:
    """Fold every peer's per-link estimates into ONE swarm topology record:
    the directed link matrix the hierarchical matchmaker (ROADMAP item 1)
    reads cliques and fat/thin peers from.

    Shape::

        {"peers": {"<label>": "<host:port>" | None, ...},
         "links": [{"src": "<label>", "dst": "<label or host:port>",
                    "dst_endpoint": "<host:port>", "rtt_s": ..,
                    "goodput_bps": .., "bytes": .., ...}, ...]}

    ``dst`` resolves to a peer label when some record advertises that
    endpoint (LocalMetrics.endpoint); otherwise the raw endpoint is kept —
    a link to a peer that never published is still a link. Returns None
    when NO peer reported link telemetry (old-schema swarm): the health
    record then simply has no topology, exactly the pre-link view."""
    peers: Dict[str, Optional[str]] = {}
    by_endpoint: Dict[str, str] = {}
    for m in records:
        endpoint = getattr(m, "endpoint", None)
        peers[m.peer] = endpoint
        if endpoint:
            by_endpoint[endpoint] = m.peer
    links: List[Dict] = []
    for m in records:
        tail = m.telemetry or {}
        for dst, fields in _peer_links(tail).items():
            links.append({
                "src": m.peer,
                "dst": by_endpoint.get(dst, dst),
                "dst_endpoint": dst,
                **fields,
            })
    if not links:
        return None
    return {"peers": peers, "links": links}


def _straggler(peers: List[Dict]) -> Optional[str]:
    """The peer most likely stalling the swarm: deepest behind the current
    step; ties (everyone current) break on the slowest step-phase wall. None
    when nothing distinguishes anyone (healthy swarm).

    behind == 1 is NOT attributed: the coordinator aggregates at the moment
    the FIRST peer's new-step record lands, so a healthy peer whose publish
    or DHT propagation lags by seconds still reads one step behind at that
    tick — naming it would warn on every step advance of a healthy fleet."""
    if not peers:
        return None
    behind = max(peers, key=lambda p: p["behind"])
    if behind["behind"] >= 2:
        return behind["peer"]
    timed = [p for p in peers if p.get("step_time_ms") is not None]
    if len(timed) >= 2:
        slowest = max(timed, key=lambda p: p["step_time_ms"])
        rest = [p["step_time_ms"] for p in timed if p is not slowest]
        # only call out a peer that is clearly off the pack (2x the mean of
        # the others) — a healthy swarm has no straggler
        if slowest["step_time_ms"] > 2.0 * (sum(rest) / len(rest) + 1e-9):
            return slowest["peer"]
    return None


def build_swarm_health(records) -> Optional[Dict]:
    """Fold fetched per-peer ``LocalMetrics`` (collaborative/metrics.py)
    into one swarm-health record. Returns None when there are no records;
    peers without a telemetry tail still contribute step/throughput rows."""
    if not records:
        return None
    current_step = max(m.step for m in records)
    peers = [_peer_entry(m, current_step) for m in records]
    attempts = sum(p["state_sync_attempts"] for p in peers)
    retries = sum(p["state_sync_retries"] for p in peers)
    formation = [
        p["round_formation_s"] for p in peers if "round_formation_s" in p
    ]
    health: Dict = {
        "current_step": current_step,
        "peers": peers,
        "straggler": _straggler(peers),
        "retry_rate": (retries / attempts) if attempts else 0.0,
        "faults_injected": sum(p["faults_injected"] for p in peers),
    }
    if formation:
        health["round_formation_s"] = sum(formation) / len(formation)
    # swarm topology (per-link telemetry): absent — not an error — when no
    # peer reports link estimates (telemetry off, or a pre-link fleet)
    topology = build_topology(records)
    if topology is not None:
        health["topology"] = topology
    return health
