"""Coordinator-side swarm-health aggregation.

Per-peer telemetry snapshots ride the signed DHT metrics bus
(``LocalMetrics.telemetry``, one RSA-signed subkey per peer — spoof-
resistant, so a peer cannot blame its retries on someone else). The
coordinator folds them into ONE swarm-health record per aggregation tick,
appended to its metrics JSONL next to the throughput aggregate: straggler
attribution, per-peer retry/fault rates, and round-formation latency — the
"why was step N slow" view the reference could only answer by reading every
volunteer's stderr.

Record shape (see docs/observability.md):

    {"current_step": N,
     "peers": [{"peer": "ab12…", "step": N, "behind": 0,
                "rpc_failures": 0.0, "rounds_attempted": 3.0,
                "phases": {"data_wait": 0.01, "fwd_bwd": 0.4, ...},  # mean s
                "dominant_phase": "fwd_bwd", "mfu": 0.57,
                "overlap_efficiency": 0.93, ...}, ...],
     "straggler": "<peer label of the worst offender, or None>",
     "retry_rate": <state-sync retries / attempts, swarm-wide>,
     "round_formation_s": <mean mm.form_group latency across peers>,
     "faults_injected": <total fault events (test harnesses only)>}

The ``phases``/``dominant_phase``/``mfu``/``overlap_*`` fields come from the
step-phase flight recorder (``telemetry/steps.py``); peers on pre-recorder
builds simply lack them — their rows fold unchanged.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from dedloc_tpu.telemetry import events

# ---------------------------------------------------------------------------
# Shared rule thresholds: ONE definition consumed by the swarm-health verdict
# below, the live watchdog (telemetry/watch.py) and the runlog_summary
# --health header — the live view and the post-hoc view can never disagree
# about what counts as DEGRADED because they read the same numbers.
# ---------------------------------------------------------------------------
RULE_THRESHOLDS: Dict[str, float] = {
    # aborted matchmaking rounds per attempted round (swarm-wide)
    "round_abort_rate": 0.25,
    # form_group attempts that never produced a group, per attempt.
    # NOT raw mm.join_failures: those count internal leader-race retries,
    # which run ~7x per formed round on a perfectly healthy contended
    # flat swarm (the ROADMAP-item-1 contention measurement) — an attempt
    # that eventually forms is a success, however many retries it took
    "join_failure_rate": 0.5,
    # connection deaths per minute, swarm-wide (needs timestamps; callers
    # without a time axis skip this rule rather than guess)
    "conns_lost_per_min": 6.0,
    # one peer's connection deaths per RPC call — a flapping NAT/firewall
    "peer_loss_ratio": 0.05,
    # steps behind the swarm before a peer is attributed (the existing
    # straggler semantics: behind==1 is publish skew, not a stall)
    "behind_steps": 2.0,
}

# counter names lifted from the instrumented seams — imported from the
# generated telemetry catalog (telemetry/events.py) so the dedlint schema
# checker guards ONE definition instead of duplicated string literals; a
# missing key reads 0.0 so peers running older builds (no telemetry tail)
# still aggregate
_PEER_COUNTERS = {
    "rpc_failures": events.RPC_CLIENT_FAILURES,
    "rpc_calls": events.RPC_CLIENT_CALLS,
    # connection-death count: with rpc_calls it gives the per-peer loss
    # rate a telemetry-fitted simulator model (dedloc_tpu/twin) reads
    "conns_lost": events.RPC_CONNS_LOST,
    "rounds_attempted": events.MM_ROUNDS_ATTEMPTED,
    "rounds_formed": events.MM_ROUNDS_FORMED,
    "rounds_aborted": events.MM_ROUNDS_ABORTED,
    "join_failures": events.MM_JOIN_FAILURES,
    "leader_changes": events.MM_LEADER_CHANGES,
    "state_sync_attempts": events.STATE_SYNC_ATTEMPTS,
    "state_sync_retries": events.STATE_SYNC_RETRIES,
    "state_sync_failures": events.STATE_SYNC_FAILURES,
    "checksum_failures": events.STATE_SYNC_CHECKSUM_FAILURES,
    "grads_dropped": events.OPT_GRADS_DROPPED,
    "grads_applied": events.OPT_GRADS_APPLIED,
    "faults_injected": events.FAULTS_APPLIED,
}


def _peer_entry(m, current_step: int) -> Dict:
    t = m.telemetry or {}
    entry: Dict = {
        "peer": m.peer,
        "step": m.step,
        "behind": max(0, current_step - m.step),
        "samples_per_second": m.samples_per_second,
    }
    if m.step_time_ms is not None:
        entry["step_time_ms"] = m.step_time_ms
    for out_key, counter in _PEER_COUNTERS.items():
        entry[out_key] = float(t.get(counter, 0.0))
    form = t.get(events.MM_FORM_GROUP + ".mean")
    if form is not None:
        entry["round_formation_s"] = float(form)
        # the matching sample count lets a streaming consumer (the
        # watchdog) recover the PER-WINDOW mean between two folds from
        # cumulative statistics: mean_w = (c2*m2 - c1*m1) / (c2 - c1)
        count = t.get(events.MM_FORM_GROUP + ".count")
        if count is not None:
            entry["round_formation_count"] = float(count)
    round_dur = t.get(events.AVG_ROUND + ".mean")
    if round_dur is not None:
        entry["round_s"] = float(round_dur)
        count = t.get(events.AVG_ROUND + ".count")
        if count is not None:
            entry["round_count"] = float(count)
    # step-phase flight recorder (telemetry/steps.py): per-phase mean
    # seconds from the snapshot's ``step.phase.<name>.mean`` histogram keys,
    # plus the dominant phase — the coordinator-side half of "why was step N
    # slow now ends in a PHASE". Absent for pre-recorder peers (no keys).
    phases = {}
    phase_counts = {}
    for key, value in t.items():
        if not isinstance(key, str) or not key.startswith("step.phase."):
            continue
        try:
            if key.endswith(".mean"):
                phases[key[len("step.phase."):-len(".mean")]] = float(value)
            elif key.endswith(".count"):
                phase_counts[
                    key[len("step.phase."):-len(".count")]
                ] = float(value)
        except (TypeError, ValueError):
            continue
    if phases:
        entry["phases"] = phases
        entry["dominant_phase"] = max(phases, key=phases.get)
        if phase_counts:
            # per-phase sample counts: the windowing companion to the
            # cumulative means (same rationale as round_count above)
            entry["phase_counts"] = phase_counts
    mfu = t.get(events.STEP_MFU)
    if mfu is not None:
        entry["mfu"] = float(mfu)
    # mean verified checkpoint-fetch goodput this peer measured against its
    # providers — an uplink-bandwidth signal for the twin fitter that
    # exists even on fleets that never ran a single averaging round
    provider_goodput = t.get(events.CKPT_PROVIDER_GOODPUT + ".mean")
    if provider_goodput is not None:
        entry["provider_goodput_bps"] = float(provider_goodput)
    # overlap ledger (collaborative optimizer): cumulative hidden/exposed
    # averaging seconds → lifetime overlap efficiency for this peer
    hidden = float(t.get(events.OPT_OVERLAP_HIDDEN_S, 0.0))
    exposed = float(t.get(events.OPT_OVERLAP_EXPOSED_S, 0.0))
    if hidden or exposed:
        entry["overlap_hidden_s"] = hidden
        entry["overlap_exposed_s"] = exposed
        entry["overlap_efficiency"] = hidden / (hidden + exposed)
    return entry


def _peer_links(tail: Dict) -> Dict[str, Dict[str, float]]:
    """Parse a snapshot's flat ``link.<dst>.<field>`` keys (telemetry/links
    LinkTable.flat) back into per-destination records. Tolerant by
    construction: snapshots that predate link telemetry simply have no
    ``link.`` keys and fold to ``{}`` — the peer keeps its ordinary
    per-peer row, it is never dropped from the fold."""
    links: Dict[str, Dict[str, float]] = {}
    for key, value in tail.items():
        if not isinstance(key, str) or not key.startswith("link."):
            continue
        # rsplit once: field names never contain dots, destinations
        # ("10.0.0.1:31337") routinely do
        dst, _, field = key[len("link."):].rpartition(".")
        if not dst or not field:
            continue
        try:
            links.setdefault(dst, {})[field] = float(value)
        except (TypeError, ValueError):
            continue
    return links


def build_topology(records) -> Optional[Dict]:
    """Fold every peer's per-link estimates into ONE swarm topology record:
    the directed link matrix the hierarchical matchmaker (ROADMAP item 1)
    reads cliques and fat/thin peers from.

    Shape::

        {"peers": {"<label>": "<host:port>" | None, ...},
         "links": [{"src": "<label>", "dst": "<label or host:port>",
                    "dst_endpoint": "<host:port>", "rtt_s": ..,
                    "goodput_bps": .., "bytes": .., ...}, ...]}

    ``dst`` resolves to a peer label when some record advertises that
    endpoint (LocalMetrics.endpoint); otherwise the raw endpoint is kept —
    a link to a peer that never published is still a link. Returns None
    when NO peer reported link telemetry (old-schema swarm): the health
    record then simply has no topology, exactly the pre-link view."""
    peers: Dict[str, Optional[str]] = {}
    by_endpoint: Dict[str, str] = {}
    for m in records:
        endpoint = getattr(m, "endpoint", None)
        peers[m.peer] = endpoint
        if endpoint:
            by_endpoint[endpoint] = m.peer
    links: List[Dict] = []
    for m in records:
        tail = m.telemetry or {}
        for dst, fields in _peer_links(tail).items():
            links.append({
                "src": m.peer,
                "dst": by_endpoint.get(dst, dst),
                "dst_endpoint": dst,
                **fields,
            })
    if not links:
        return None
    return {"peers": peers, "links": links}


def _straggler(peers: List[Dict]) -> Optional[str]:
    """The peer most likely stalling the swarm: deepest behind the current
    step; ties (everyone current) break on the slowest step-phase wall. None
    when nothing distinguishes anyone (healthy swarm).

    behind == 1 is NOT attributed: the coordinator aggregates at the moment
    the FIRST peer's new-step record lands, so a healthy peer whose publish
    or DHT propagation lags by seconds still reads one step behind at that
    tick — naming it would warn on every step advance of a healthy fleet."""
    if not peers:
        return None
    behind = max(peers, key=lambda p: p["behind"])
    if behind["behind"] >= RULE_THRESHOLDS["behind_steps"]:
        return behind["peer"]
    timed = [p for p in peers if p.get("step_time_ms") is not None]
    if len(timed) >= 2:
        slowest = max(timed, key=lambda p: p["step_time_ms"])
        rest = [p["step_time_ms"] for p in timed if p is not slowest]
        # only call out a peer that is clearly off the pack (2x the mean of
        # the others) — a healthy swarm has no straggler
        if slowest["step_time_ms"] > 2.0 * (sum(rest) / len(rest) + 1e-9):
            return slowest["peer"]
    return None


def derive_rates(
    health: Dict,
    prev: Optional[Dict] = None,
    dt_s: Optional[float] = None,
) -> Dict[str, float]:
    """Swarm-level derived rates the rule detectors read — computed from
    ONE swarm-health record's cumulative counters, or WINDOWED between two
    consecutive records when ``prev`` is given (the streaming watchdog's
    case; ``dt_s`` additionally unlocks the per-minute rates).

    Returned keys (each absent when its inputs are, never guessed):
    ``round_abort_rate``, ``join_failure_rate``, ``conns_lost`` (count over
    the window / lifetime), ``conns_lost_per_min`` (needs ``dt_s``),
    ``peer_loss_ratio`` (the worst peer's conns-lost per RPC call) and
    ``peer_loss_ratio_peer`` (who that is)."""

    def total(record: Optional[Dict], key: str) -> float:
        if not record:
            return 0.0
        return sum(
            float(p.get(key, 0.0)) for p in record.get("peers", [])
            if isinstance(p, dict)
        )

    def window(key: str) -> float:
        # clamped at 0: a peer set that shrank (churn) can make the
        # cumulative swarm sum regress without anything "un-happening"
        return max(0.0, total(health, key) - total(prev, key))

    rates: Dict[str, float] = {}
    attempted = window("rounds_attempted")
    aborted = window("rounds_aborted")
    if attempted > 0:
        rates["round_abort_rate"] = round(aborted / attempted, 4)
    formed = window("rounds_formed")
    if attempted > 0:
        # attempts that never produced a group (clamped: formed can lag
        # attempted by in-flight rounds at the fold boundary)
        rates["join_failure_rate"] = round(
            max(0.0, attempted - formed) / attempted, 4
        )
        # informational contention gauge, no rule attached: internal
        # leader-race retries per attempt — high on any contended flat
        # swarm, interesting for sizing, not an incident
        rates["join_retries_per_attempt"] = round(
            window("join_failures") / attempted, 2
        )
    conns_lost = window("conns_lost")
    rates["conns_lost"] = round(conns_lost, 1)
    if dt_s is not None and dt_s > 0:
        rates["conns_lost_per_min"] = round(conns_lost / (dt_s / 60.0), 3)
    worst_ratio, worst_peer = 0.0, None
    for p in health.get("peers", []):
        if not isinstance(p, dict):
            continue
        calls = float(p.get("rpc_calls", 0.0))
        lost = float(p.get("conns_lost", 0.0))
        # ratios stay cumulative even in windowed mode: per-peer windows
        # need the prev record's matching peer row, and a lifetime ratio
        # is the conservative (non-flapping) reading for a rule threshold
        if calls >= 20 and lost / calls > worst_ratio:
            worst_ratio, worst_peer = lost / calls, p.get("peer")
    if worst_peer is not None and worst_ratio > 0:
        rates["peer_loss_ratio"] = round(worst_ratio, 4)
        rates["peer_loss_ratio_peer"] = worst_peer
    return rates


def verdict_from_rates(
    rates: Dict[str, Any], straggler: Optional[str] = None
) -> Tuple[str, str]:
    """("OK"|"DEGRADED", reason) from a derived-rates dict — THE shared
    rule evaluation: ``runlog_summary --health``'s header, the coordinator
    fold and the watchdog all call this with RULE_THRESHOLDS applied to
    whatever rates their input could support."""
    reasons: List[str] = []
    for key in ("round_abort_rate", "join_failure_rate",
                "conns_lost_per_min", "peer_loss_ratio"):
        value = rates.get(key)
        if value is None:
            continue
        limit = RULE_THRESHOLDS[key]
        if float(value) > limit:
            tag = f"{key} {float(value):.3g} > {limit:g}"
            if key == "peer_loss_ratio" and rates.get(
                "peer_loss_ratio_peer"
            ):
                tag += f" ({rates['peer_loss_ratio_peer']})"
            reasons.append(tag)
    if straggler:
        reasons.append(f"straggler {straggler}")
    if reasons:
        return "DEGRADED", "; ".join(reasons)
    return "OK", "all rule rates within thresholds"


def build_swarm_health(records, rounds: Optional[List[Dict]] = None,
                       prev: Optional[Dict] = None,
                       dt_s: Optional[float] = None) -> Optional[Dict]:
    """Fold fetched per-peer ``LocalMetrics`` (collaborative/metrics.py)
    into one swarm-health record. Returns None when there are no records;
    peers without a telemetry tail still contribute step/throughput rows.

    ``rounds`` (optional) attaches recent round summaries
    (``[{"round_id", "peer", "dur_s", "ok", "trace"?}, ...]``) when the
    folder has them — the simulator's coordinator fold does; the production
    metrics bus carries only flat floats, so a live coordinator's records
    simply lack the field and the watchdog reports that in its coverage.
    ``prev``/``dt_s`` window the derived rates against the previous fold."""
    if not records:
        return None
    current_step = max(m.step for m in records)
    peers = [_peer_entry(m, current_step) for m in records]
    attempts = sum(p["state_sync_attempts"] for p in peers)
    retries = sum(p["state_sync_retries"] for p in peers)
    formation = [
        p["round_formation_s"] for p in peers if "round_formation_s" in p
    ]
    health: Dict = {
        "current_step": current_step,
        "peers": peers,
        "straggler": _straggler(peers),
        "retry_rate": (retries / attempts) if attempts else 0.0,
        "faults_injected": sum(p["faults_injected"] for p in peers),
    }
    if formation:
        health["round_formation_s"] = sum(formation) / len(formation)
    if rounds:
        health["rounds"] = rounds
    # swarm topology (per-link telemetry): absent — not an error — when no
    # peer reports link estimates (telemetry off, or a pre-link fleet)
    topology = build_topology(records)
    if topology is not None:
        health["topology"] = topology
    # swarm-level derived rates + the one-line verdict, from the SAME rule
    # set the watchdog runs (RULE_THRESHOLDS) — the fold and the live view
    # cannot disagree
    rates = derive_rates(health, prev=prev, dt_s=dt_s)
    health["derived"] = rates
    status, reason = verdict_from_rates(rates, health["straggler"])
    health["verdict"] = {"status": status, "reason": reason}
    return health
