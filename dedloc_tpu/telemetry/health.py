"""Coordinator-side swarm-health aggregation.

Per-peer telemetry snapshots ride the signed DHT metrics bus
(``LocalMetrics.telemetry``, one RSA-signed subkey per peer — spoof-
resistant, so a peer cannot blame its retries on someone else). The
coordinator folds them into ONE swarm-health record per aggregation tick,
appended to its metrics JSONL next to the throughput aggregate: straggler
attribution, per-peer retry/fault rates, and round-formation latency — the
"why was step N slow" view the reference could only answer by reading every
volunteer's stderr.

Record shape (see docs/observability.md):

    {"current_step": N,
     "peers": [{"peer": "ab12…", "step": N, "behind": 0,
                "rpc_failures": 0.0, "rounds_attempted": 3.0, ...}, ...],
     "straggler": "<peer label of the worst offender, or None>",
     "retry_rate": <state-sync retries / attempts, swarm-wide>,
     "round_formation_s": <mean mm.form_group latency across peers>,
     "faults_injected": <total fault events (test harnesses only)>}
"""
from __future__ import annotations

from typing import Dict, List, Optional

# counter names lifted from the instrumented seams; a missing key reads 0.0
# so peers running older builds (no telemetry tail) still aggregate
_PEER_COUNTERS = {
    "rpc_failures": "rpc.client.failures",
    "rpc_calls": "rpc.client.calls",
    "rounds_attempted": "mm.rounds_attempted",
    "rounds_formed": "mm.rounds_formed",
    "rounds_aborted": "mm.rounds_aborted",
    "join_failures": "mm.join_failures",
    "leader_changes": "mm.leader_changes",
    "state_sync_attempts": "state_sync.attempts",
    "state_sync_retries": "state_sync.retries",
    "state_sync_failures": "state_sync.failures",
    "checksum_failures": "state_sync.checksum_failures",
    "grads_dropped": "opt.grads_dropped",
    "grads_applied": "opt.grads_applied",
    "faults_injected": "faults.applied",
}


def _peer_entry(m, current_step: int) -> Dict:
    t = m.telemetry or {}
    entry: Dict = {
        "peer": m.peer,
        "step": m.step,
        "behind": max(0, current_step - m.step),
        "samples_per_second": m.samples_per_second,
    }
    if m.step_time_ms is not None:
        entry["step_time_ms"] = m.step_time_ms
    for out_key, counter in _PEER_COUNTERS.items():
        entry[out_key] = float(t.get(counter, 0.0))
    form = t.get("mm.form_group.mean")
    if form is not None:
        entry["round_formation_s"] = float(form)
    round_dur = t.get("avg.round.mean")
    if round_dur is not None:
        entry["round_s"] = float(round_dur)
    return entry


def _straggler(peers: List[Dict]) -> Optional[str]:
    """The peer most likely stalling the swarm: deepest behind the current
    step; ties (everyone current) break on the slowest step-phase wall. None
    when nothing distinguishes anyone (healthy swarm).

    behind == 1 is NOT attributed: the coordinator aggregates at the moment
    the FIRST peer's new-step record lands, so a healthy peer whose publish
    or DHT propagation lags by seconds still reads one step behind at that
    tick — naming it would warn on every step advance of a healthy fleet."""
    if not peers:
        return None
    behind = max(peers, key=lambda p: p["behind"])
    if behind["behind"] >= 2:
        return behind["peer"]
    timed = [p for p in peers if p.get("step_time_ms") is not None]
    if len(timed) >= 2:
        slowest = max(timed, key=lambda p: p["step_time_ms"])
        rest = [p["step_time_ms"] for p in timed if p is not slowest]
        # only call out a peer that is clearly off the pack (2x the mean of
        # the others) — a healthy swarm has no straggler
        if slowest["step_time_ms"] > 2.0 * (sum(rest) / len(rest) + 1e-9):
            return slowest["peer"]
    return None


def build_swarm_health(records) -> Optional[Dict]:
    """Fold fetched per-peer ``LocalMetrics`` (collaborative/metrics.py)
    into one swarm-health record. Returns None when there are no records;
    peers without a telemetry tail still contribute step/throughput rows."""
    if not records:
        return None
    current_step = max(m.step for m in records)
    peers = [_peer_entry(m, current_step) for m in records]
    attempts = sum(p["state_sync_attempts"] for p in peers)
    retries = sum(p["state_sync_retries"] for p in peers)
    formation = [
        p["round_formation_s"] for p in peers if "round_formation_s" in p
    ]
    health: Dict = {
        "current_step": current_step,
        "peers": peers,
        "straggler": _straggler(peers),
        "retry_rate": (retries / attempts) if attempts else 0.0,
        "faults_injected": sum(p["faults_injected"] for p in peers),
    }
    if formation:
        health["round_formation_s"] = sum(formation) / len(formation)
    return health
