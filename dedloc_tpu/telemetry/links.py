"""Per-link network telemetry: RTT + goodput estimates for every (src, dst)
pair this peer talks to.

DeDLOC's averaging strategy adapts to per-peer bandwidth and reliability
(PAPER.md §0), and the hierarchical-topology work (ROADMAP item 1) needs to
learn cliques from *link*-level latency — data the per-peer counters cannot
provide: ``net.bytes_out`` says how much this peer sent, not over which link
or how fast that link ran. This module derives directed per-link estimates
from traffic the peer already generates:

- **RTT**: the TCP connect handshake on every pooled RPC connection is a
  free SYN/SYN-ACK round trip — ``RPCClient._connect`` times it (the "cheap
  piggybacked ping on connection setup"; no new traffic on the hot path).
- **Goodput + chunk latency**: the pipelined all-reduce times every chunk it
  scatters/gathers per destination (``averaging/allreduce.py``), and the
  sharded checkpoint fetcher times every shard per provider
  (``checkpointing/fetcher.py``). Each observation is wire payload bytes
  over wall seconds.

Estimates are EWMAs (recent behavior wins — a link that degraded an hour
into the run must show it) plus a bounded recent-latency window for
percentiles. The table is bounded (``max_links``) and its snapshot is
top-K by traffic, so a thousand-peer swarm cannot bloat a peer's signed
metrics-bus record.

Publication paths, both bounded and both ``_active``-gated:

- ``Telemetry.snapshot()`` folds ``flat(top_k)`` — keys like
  ``link.<host:port>.rtt_s`` / ``.goodput_bps`` — into the flat snapshot
  that rides the signed DHT metrics bus; the coordinator folds those into
  the swarm topology record (``telemetry/health.py``).
- ``emit_events`` mirrors one ``link.stats`` event per tracked link into
  the per-peer JSONL event log (on the snapshot throttle and at close), so
  ``tools/runlog_summary.py --topology`` renders a link matrix from event
  logs alone.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

# EWMA weight of the newest sample. 0.25 ≈ the last ~8 samples dominate:
# reactive enough to catch a link that degrades mid-run, smooth enough that
# one GC pause or retransmit burst does not rewrite the topology
DEFAULT_ALPHA = 0.25


def endpoint_key(endpoint) -> str:
    """Canonical string key for a link destination: ``"host:port"``. Accepts
    (host, port) tuples/lists or a preformatted string."""
    if isinstance(endpoint, str):
        return endpoint
    try:
        host, port = endpoint[0], endpoint[1]
        return f"{host}:{int(port)}"
    except (TypeError, IndexError, ValueError):
        return str(endpoint)


class LinkStats:
    """One directed link (this peer → ``dst``)."""

    WINDOW = 64

    __slots__ = (
        "dst", "rtt_s", "rtt_samples", "rtt_jitter_s", "rtt_min_s",
        "goodput_bps", "bytes", "transfers", "_recent_s", "_recent_bps",
        "last_seq",
    )

    def __init__(self, dst: str) -> None:
        self.dst = dst
        self.rtt_s: Optional[float] = None
        self.rtt_samples = 0
        # EWMA of |sample - estimate|: the link's delay variation, the
        # jitter the simulator's LinkSpec.jitter_s models (a digital twin
        # fitted from this table needs spread, not just the center)
        self.rtt_jitter_s = 0.0
        # fastest sample ever: connect timings ride the caller's event
        # loop, so every sample carries scheduling noise ON TOP of the
        # wire round trip — the minimum is the cleanest base-RTT estimate
        # (the one a fitted simulator model should pay per hop)
        self.rtt_min_s: Optional[float] = None
        self.goodput_bps: Optional[float] = None
        self.bytes = 0
        self.transfers = 0
        self._recent_s: Deque[float] = deque(maxlen=self.WINDOW)
        # recent per-transfer rates: ``peak_bps`` (the best of them) is the
        # least-CONTENDED observation — transfers time wall while the
        # sender's uplink is shared, so the EWMA reads effective goodput
        # under load, while the peak approaches raw link bandwidth. A
        # fitted simulator model must use the peak: it re-simulates the
        # contention itself, and seeding it with contended goodput would
        # charge the queueing twice.
        self._recent_bps: Deque[float] = deque(maxlen=self.WINDOW)
        # observation sequence number (table-wide): eviction order when the
        # table is full — the STALEST link yields, never the newest
        self.last_seq = 0

    def chunk_percentile(self, p: float) -> float:
        if not self._recent_s:
            return 0.0
        s = sorted(self._recent_s)
        return s[min(len(s) - 1, int(p * len(s)))]

    def record(self) -> Dict[str, float]:
        """This link's estimate as one flat dict (the ``link.stats`` event
        payload and the --topology row)."""
        out: Dict[str, float] = {
            "dst": self.dst,
            "bytes": float(self.bytes),
            "transfers": float(self.transfers),
        }
        if self.rtt_s is not None:
            out["rtt_s"] = round(self.rtt_s, 6)
            if self.rtt_min_s is not None:
                out["rtt_min_s"] = round(self.rtt_min_s, 6)
            if self.rtt_samples >= 2:
                out["rtt_jitter_s"] = round(self.rtt_jitter_s, 6)
        if self.goodput_bps is not None:
            out["goodput_bps"] = round(self.goodput_bps, 1)
        if self._recent_bps:
            out["peak_bps"] = round(max(self._recent_bps), 1)
        if self._recent_s:
            out["chunk_p50_s"] = round(self.chunk_percentile(0.50), 6)
            out["chunk_max_s"] = round(max(self._recent_s), 6)
        return out


class LinkTable:
    """Bounded registry of per-destination link estimates. Thread-safe: the
    DHT loop (allreduce, restores) and the trainer thread (snapshots) both
    touch it."""

    def __init__(
        self, alpha: float = DEFAULT_ALPHA, max_links: int = 64
    ) -> None:
        self.alpha = float(alpha)
        self.max_links = int(max_links)
        self._links: Dict[str, LinkStats] = {}
        self._seq = 0  # observation counter: staleness order for eviction
        self._lock = threading.Lock()

    def _link(self, dst) -> LinkStats:
        """The stats record for ``dst``, touching its staleness marker. The
        table stays bounded by EVICTING the least-recently-observed link
        when full: on a churning swarm the links a peer currently talks
        over stay tracked, and estimates for departed peers age out instead
        of squatting the table forever."""
        key = endpoint_key(dst)
        self._seq += 1
        link = self._links.get(key)
        if link is None:
            if len(self._links) >= self.max_links:
                stalest = min(
                    self._links.values(), key=lambda l: l.last_seq
                )
                del self._links[stalest.dst]
            link = self._links[key] = LinkStats(key)
        link.last_seq = self._seq
        return link

    def observe_rtt(self, dst, rtt_s: float) -> None:
        if rtt_s < 0:
            return
        with self._lock:
            link = self._link(dst)
            if link.rtt_s is None:
                link.rtt_s = float(rtt_s)
            else:
                # deviation against the PRE-update estimate: the first
                # sample contributes zero jitter by construction
                link.rtt_jitter_s += self.alpha * (
                    abs(float(rtt_s) - link.rtt_s) - link.rtt_jitter_s
                )
                link.rtt_s += self.alpha * (float(rtt_s) - link.rtt_s)
            link.rtt_min_s = (
                float(rtt_s) if link.rtt_min_s is None
                else min(link.rtt_min_s, float(rtt_s))
            )
            link.rtt_samples += 1

    def observe_transfer(self, dst, nbytes: int, seconds: float) -> None:
        """One wire transfer (chunk, shard, blob) to/from ``dst``:
        ``nbytes`` payload bytes over ``seconds`` wall. Degenerate timings
        (clock granularity, loopback) are clamped, not dropped — a 0-second
        transfer is evidence of a FAST link."""
        if nbytes <= 0:
            return
        seconds = max(float(seconds), 1e-6)
        sample_bps = nbytes / seconds
        with self._lock:
            link = self._link(dst)
            if link.goodput_bps is None:
                link.goodput_bps = sample_bps
            else:
                link.goodput_bps += self.alpha * (
                    sample_bps - link.goodput_bps
                )
            link.bytes += int(nbytes)
            link.transfers += 1
            link._recent_s.append(seconds)
            link._recent_bps.append(sample_bps)

    # ---------------------------------------------------------- publication

    def top(self, k: Optional[int] = None) -> List[LinkStats]:
        """Tracked links, busiest (most bytes, then most RTT samples)
        first, truncated to ``k``."""
        with self._lock:
            links = sorted(
                self._links.values(),
                key=lambda l: (-l.bytes, -l.rtt_samples, l.dst),
            )
        return links if k is None else links[: max(0, k)]

    def flat(self, top_k: int = 8) -> Dict[str, float]:
        """Flat ``{"link.<dst>.<field>": value}`` view of the top-K links —
        the shape that rides the metrics-bus telemetry snapshot (every value
        a float; ``dst`` strings live in the key)."""
        # dedlint: emits=link.* — these snapshot keys are built by hand
        # below, not through a registry call, so the telemetry catalog
        # learns the family from this declaration
        out: Dict[str, float] = {}
        for link in self.top(top_k):
            rec = link.record()
            rec.pop("dst", None)
            for field, value in rec.items():
                out[f"link.{link.dst}.{field}"] = float(value)
        return out

    def records(self, top_k: Optional[int] = None) -> List[Dict[str, float]]:
        return [link.record() for link in self.top(top_k)]

    def emit_events(self, telemetry) -> None:
        """Mirror the current estimates into ``telemetry``'s event log: one
        ``link.stats`` event per tracked link (bounded by the registry's
        ``link_top_k``)."""
        for rec in self.records(getattr(telemetry, "link_top_k", 8)):
            telemetry.event("link.stats", **rec)
