"""Swarm telemetry layer: counters + span tracing across DHT / averaging /
optimizer, with coordinator swarm-health aggregation.

See ``registry`` (the per-peer metric registry + event trace, zero overhead
when disabled), ``health`` (coordinator aggregation over the signed metrics
bus), and docs/observability.md for the operator view.
"""
from __future__ import annotations

from typing import Optional

from dedloc_tpu.telemetry import registry, steps
from dedloc_tpu.telemetry.health import (
    RULE_THRESHOLDS,
    build_swarm_health,
    build_topology,
    derive_rates,
    verdict_from_rates,
)
from dedloc_tpu.telemetry.links import LinkTable, endpoint_key
from dedloc_tpu.telemetry.watch import SwarmWatch, WatchConfig, watch_rows
from dedloc_tpu.telemetry.steps import StepRecorder
from dedloc_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    Telemetry,
    active,
    adopt_trace,
    current_trace,
    enabled,
    event,
    inc,
    install,
    monotonic_clock,
    new_span_id,
    resolve,
    span,
    trace_id_for,
    uninstall,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "LinkTable",
    "RULE_THRESHOLDS",
    "StepRecorder",
    "SwarmWatch",
    "Telemetry",
    "WatchConfig",
    "active",
    "adopt_trace",
    "build_swarm_health",
    "build_topology",
    "configure",
    "current_trace",
    "derive_rates",
    "enabled",
    "endpoint_key",
    "event",
    "inc",
    "install",
    "verdict_from_rates",
    "watch_rows",
    "monotonic_clock",
    "new_span_id",
    "registry",
    "resolve",
    "span",
    "steps",
    "trace_id_for",
    "uninstall",
]


def configure(args, peer: str = "") -> Optional[Telemetry]:
    """Role-entry wiring: install the process-global registry from a
    ``TelemetryArguments`` block (core/config.py ``--telemetry.*`` knobs).
    Returns the installed registry, or None when telemetry is disabled —
    the instrumented seams then cost one attribute load each."""
    if not getattr(args, "enabled", False):
        return None
    return install(
        Telemetry(
            peer=peer,
            event_log_path=args.event_log_path or None,
            link_top_k=getattr(args, "link_top_k", 8),
        )
    )
