"""Swarm telemetry layer: counters + span tracing across DHT / averaging /
optimizer, with coordinator swarm-health aggregation.

See ``registry`` (the per-peer metric registry + event trace, zero overhead
when disabled), ``health`` (coordinator aggregation over the signed metrics
bus), and docs/observability.md for the operator view.
"""
from __future__ import annotations

from typing import Optional

from dedloc_tpu.telemetry import registry
from dedloc_tpu.telemetry.health import build_swarm_health
from dedloc_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    Telemetry,
    active,
    enabled,
    event,
    inc,
    install,
    monotonic_clock,
    resolve,
    span,
    uninstall,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Telemetry",
    "active",
    "build_swarm_health",
    "configure",
    "enabled",
    "event",
    "inc",
    "install",
    "monotonic_clock",
    "registry",
    "resolve",
    "span",
    "uninstall",
]


def configure(args, peer: str = "") -> Optional[Telemetry]:
    """Role-entry wiring: install the process-global registry from a
    ``TelemetryArguments`` block (core/config.py ``--telemetry.*`` knobs).
    Returns the installed registry, or None when telemetry is disabled —
    the instrumented seams then cost one attribute load each."""
    if not getattr(args, "enabled", False):
        return None
    return install(
        Telemetry(peer=peer, event_log_path=args.event_log_path or None)
    )
