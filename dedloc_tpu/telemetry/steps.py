"""Per-step flight recorder: in-situ hot-path attribution for the trainer.

``tools/profile_albert.py`` answers "where do the cycles go" offline, by
marginal-cost ablation on an idle chip (docs/perf.md). This module answers
the *production* form of the question — "where did step N's wall-clock go,
on this peer, in this run" — by decomposing every training step into named
phases and publishing the breakdown through the existing telemetry registry
(events + histograms + gauges), so the coordinator's swarm-health fold and
``runlog_summary --steps`` can rank peers by phase skew without attaching a
profiler to a volunteer's box.

Canonical phases (docs/observability.md "Step-phase flight recorder"):

- ``data_wait``    host input-pipeline stall (``next(batches)``)
- ``h2d``          host→device batch transfer (``put_batch`` on a mesh)
- ``fwd_bwd``      jitted accumulate dispatch + the boundary's
                   ``block_until_ready`` (XLA runs async — without the
                   block a timer measures dispatch, not execution)
- ``grad_flatten`` launching the device-side flatten/quantize program (or,
                   on the legacy path, the per-leaf device_get + host
                   flatten of the mean grads — the jit↔host seam crossing)
- ``d2h_stream``   the EXPOSED remainder of the async device→host gradient
                   stream: the transfer overlaps matchmaking (and, in
                   overlap mode, accumulation), so this phase reads ~0
                   when the overlap works and grows when the link is the
                   bottleneck (averaging/device_flat.py)
- ``avg_wire``     the synchronous averaging round (matchmaking + wire),
                   net of the exposed D2H wait above
- ``opt_apply``    optimizer apply + NaN guard
- ``collab``       progress-tracker reads/reports (DHT overhead)

Phase names are open — instrumented code may record others — but the six
canonical ones are what the cross-peer skew views key on. Phases must be
DISJOINT (never nest two live phases): the whole point of the recorder is
that per-step phase sums track the step wall, so the residual
(``untimed_s``) measures what the instrumentation missed.

Design rules, mirroring ``registry.py``:

- **Zero overhead when disabled.** ``StepRecorder.step`` resolves the
  telemetry registry once; with telemetry off it yields ``None`` and sets
  no context, and the module-level ``phase()`` helper used by code that
  does not hold the recorder (the collaborative optimizer) is a single
  contextvar load returning a shared no-op.
- **FakeClock-compatible.** All timing uses the registry's monotonic
  clock (``registry.monotonic_clock``), which advances with the FakeClock
  offset — fault-injection tests produce deterministic phase durations.
- **One event per phase plus one summary.** Each finished step emits a
  ``step.phase`` event per recorded phase and one ``step.record`` event
  carrying the full breakdown (wall, samples, per-phase seconds, untimed
  residual, dominant phase, online MFU); each phase also feeds the
  ``step.phase.<name>`` histogram so metrics-bus snapshots carry
  ``step.phase.<name>.mean`` for the coordinator's swarm-health fold.
"""
from __future__ import annotations

import contextvars
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, Optional

from dedloc_tpu.telemetry import registry

# the canonical phase set, in pipeline order — the cross-peer views key on
# these (tools/runlog_summary.py keeps a deliberate copy, _CANONICAL_PHASES,
# because the tool is stdlib-only; keep the two in sync)
PHASES = (
    "data_wait", "h2d", "fwd_bwd", "grad_flatten", "d2h_stream", "avg_wire",
    "opt_apply", "collab",
)

# bf16 peak TFLOP/s per chip by PJRT device_kind substring — the same table
# bench.py uses for the offline MFU report, duplicated here because bench.py
# is a repo-root script, not an importable package module. Keep in sync.
TPU_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
    ("v6 lite", 918.0),  # trillium
)


def chip_peak_tflops() -> float:
    """Peak bf16 TFLOP/s of device 0, or 0.0 off-TPU (MFU gauge omitted)."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — telemetry must never kill training
        return 0.0
    for sub, peak in TPU_PEAK_TFLOPS:
        if sub in kind:
            return peak
    return 0.0


def albert_tflops_per_sample(cfg, seq: int, max_pred: int) -> float:
    """Analytic MODEL TFLOPs for one ALBERT fwd+bwd sample — the same
    matmul-only formula as bench.py's ``albert_train_flops_per_sample``
    (remat recompute excluded by convention), so the recorder's in-situ MFU
    gauge is directly comparable to the BENCH_r* ``mfu`` field."""
    h, i, s = cfg.hidden_size, cfg.intermediate_size, seq
    e, v = cfg.embedding_size, cfg.vocab_size
    per_token_layer = 8 * h * h + 4 * h * s + 4 * h * i
    fwd = cfg.num_hidden_layers * per_token_layer * s
    fwd += 2 * e * h * s
    fwd += max_pred * 2 * (h * e + e * v)
    fwd += 2 * h * 2
    return 3.0 * fwd / 1e12


class _StepContext:
    """The live step being recorded: a mutable phase ledger plus free-form
    attrs (``ctx.attrs["stepped"] = True``) merged into the final record."""

    __slots__ = ("phases", "attrs", "step", "samples", "_clock")

    def __init__(self, step: Optional[int], samples: int, clock) -> None:
        self.phases: Dict[str, float] = {}
        self.attrs: Dict[str, Any] = {}
        self.step = step
        self.samples = int(samples)
        self._clock = clock

    def add(self, name: str, seconds: float) -> None:
        """Credit ``seconds`` to phase ``name`` (accumulates — a phase may
        be entered many times per step, e.g. data_wait per micro-batch)."""
        self.phases[name] = self.phases.get(name, 0.0) + max(0.0, seconds)

    @contextmanager
    def phase(self, name: str, block_on: Any = None) -> Iterator[None]:
        """Time a region into phase ``name``. ``block_on``: pytree of jax
        arrays blocked on before the clock stops (the TPU analogue of
        CUDA-event timing — XLA dispatch is async)."""
        start = self._clock()
        try:
            yield
        finally:
            if block_on is not None:
                import jax

                jax.block_until_ready(block_on)
            self.add(name, self._clock() - start)


# the live step context (per-thread / per-task): instrumented code that does
# not hold the recorder — the collaborative optimizer's grad_flatten /
# avg_wire / opt_apply seams — attributes its phases through this
_CURRENT: contextvars.ContextVar[Optional[_StepContext]] = (
    contextvars.ContextVar("dedloc_step", default=None)
)


def current() -> Optional[_StepContext]:
    return _CURRENT.get()


@contextmanager
def _null() -> Iterator[None]:
    yield


def phase(name: str, block_on: Any = None):
    """Module-level phase timer: times into the innermost live step record,
    or no-ops (one contextvar load) when no step is being recorded."""
    ctx = _CURRENT.get()
    return ctx.phase(name, block_on) if ctx is not None else _null()


def add(name: str, seconds: float) -> None:
    """Credit pre-measured seconds to the live step record (no-op when none
    is live) — for call sites that already hold a duration."""
    ctx = _CURRENT.get()
    if ctx is not None:
        ctx.add(name, seconds)


class StepRecorder:
    """Bounded ring of per-step phase breakdowns + an online MFU gauge.

    One recorder per trainer loop. ``model_tflops_per_sample`` and
    ``peak_tflops`` enable the MFU gauge (0 disables it — e.g. CPU smoke
    runs); throughput for the gauge is a ring-window mean (samples over
    recorded wall), so it tracks the same quantity the bench headline
    measures rather than a single noisy step.
    """

    def __init__(
        self,
        telemetry: Optional[registry.Telemetry] = None,
        model_tflops_per_sample: float = 0.0,
        peak_tflops: float = 0.0,
        ring: int = 256,
        mfu_window: int = 32,
    ) -> None:
        self.telemetry = telemetry
        self.model_tflops_per_sample = float(model_tflops_per_sample)
        self.peak_tflops = float(peak_tflops)
        self.records: Deque[Dict[str, Any]] = deque(maxlen=ring)
        self.mfu_window = int(mfu_window)

    @contextmanager
    def step(
        self, step: Optional[int] = None, samples: int = 0
    ) -> Iterator[Optional[_StepContext]]:
        """Record one training step. Yields the live ``_StepContext`` (or
        None with telemetry disabled — callers use the yielded value only
        behind an ``is not None`` check, the disabled path costs one
        resolve)."""
        tele = registry.resolve(self.telemetry)
        if tele is None:
            yield None
            return
        ctx = _StepContext(step, samples, tele.clock)
        token = _CURRENT.set(ctx)
        start = tele.clock()
        try:
            yield ctx
        finally:
            _CURRENT.reset(token)
            wall = max(0.0, tele.clock() - start)
            self._finish(tele, ctx, wall)

    # ------------------------------------------------------------- internal

    def _finish(
        self, tele: registry.Telemetry, ctx: _StepContext, wall: float
    ) -> None:
        phases = dict(ctx.phases)
        untimed = max(0.0, wall - sum(phases.values()))
        record: Dict[str, Any] = {
            "step": ctx.step,
            "samples": ctx.samples,
            "wall_s": wall,
            "phases": phases,
            "untimed_s": untimed,
            **ctx.attrs,
        }
        dominant = max(phases, key=phases.get) if phases else None
        if dominant is not None:
            record["dominant"] = dominant
        mfu = self._update_mfu(tele, record)
        if mfu is not None:
            record["mfu"] = mfu
        self.records.append(record)
        tele.histogram("step.wall").observe(wall)
        for name, dur in phases.items():
            tele.histogram(f"step.phase.{name}").observe(dur)
            tele.event("step.phase", phase=name, dur_s=dur, step=ctx.step)
        tele.event("step.record", dur_s=wall, **{
            k: v for k, v in record.items() if k != "wall_s"
        })

    def _update_mfu(self, tele, record) -> Optional[float]:
        if self.model_tflops_per_sample <= 0 or self.peak_tflops <= 0:
            return None
        # ``record`` is not in the ring yet — append before slicing so
        # mfu_window=1 means "this step only", not the whole ring
        recent = (list(self.records) + [record])[-self.mfu_window:]
        samples = sum(r["samples"] for r in recent)
        wall = sum(r["wall_s"] for r in recent)
        if samples <= 0 or wall <= 0:
            return None
        sps = samples / wall
        mfu = sps * self.model_tflops_per_sample / self.peak_tflops
        tele.gauge("step.samples_per_sec").set(sps)
        tele.gauge("step.mfu").set(mfu)
        return mfu
