"""Telemetry name catalog — GENERATED, do not edit by hand.

Regenerate after adding/renaming any emitted counter/gauge/
histogram/span/event name::

    python -m tools.dedlint --write-events

The dedlint schema checker (tools/dedlint) extracts every name
emitted through telemetry/registry.py call sites (plus declared
dynamic prefixes) and fails tier-1 when this file is stale or when
a consumer reads a key nothing emits (docs/contributor.md).
"""

ALLREDUCE_BYTES_RECEIVED = "allreduce.bytes_received"
ALLREDUCE_BYTES_SENT = "allreduce.bytes_sent"
ALLREDUCE_CHUNK_LATENCY_S = "allreduce.chunk_latency_s"
ALLREDUCE_CHUNKS_RECEIVED = "allreduce.chunks_received"
ALLREDUCE_CHUNKS_SENT = "allreduce.chunks_sent"
ALLREDUCE_FAILURES = "allreduce.failures"
ALLREDUCE_LINK = "allreduce.link"
ALLREDUCE_ROUND = "allreduce.round"
ALLREDUCE_ROUNDS = "allreduce.rounds"
ALLREDUCE_STRAGGLERS = "allreduce.stragglers"
AVG_BYTES_SAVED = "avg.bytes_saved"
AVG_ROUND = "avg.round"
AVG_TOPOLOGY_FALLBACK = "avg.topology.fallback"
AVG_TOPOLOGY_FALLBACKS = "avg.topology.fallbacks"
AVG_TOPOLOGY_PLAN = "avg.topology.plan"
AVG_TOPOLOGY_REPLAN = "avg.topology.replan"
AVG_TOPOLOGY_REPLANS = "avg.topology.replans"
AVG_TOPOLOGY_ROUND = "avg.topology.round"
AVG_TOPOLOGY_ROUNDS = "avg.topology.rounds"
CKPT_FETCH_FAILURES = "ckpt.fetch_failures"
CKPT_FETCH_RETRIES = "ckpt.fetch_retries"
CKPT_MANIFEST_SERVE = "ckpt.manifest.serve"
CKPT_MANIFEST_WRITTEN = "ckpt.manifest_written"
CKPT_MANIFESTS_WRITTEN = "ckpt.manifests_written"
CKPT_PROVIDER_GOODPUT = "ckpt.provider_goodput"
CKPT_RESTORE = "ckpt.restore"
CKPT_RESTORE_FAILURES = "ckpt.restore_failures"
CKPT_RESTORES = "ckpt.restores"
CKPT_SHARD_SERVE = "ckpt.shard.serve"
CKPT_SHARD_BYTES_FETCHED = "ckpt.shard_bytes_fetched"
CKPT_SHARD_BYTES_SERVED = "ckpt.shard_bytes_served"
CKPT_SHARD_FETCH_FAILED = "ckpt.shard_fetch_failed"
CKPT_SHARD_VERIFY_FAILURE = "ckpt.shard_verify_failure"
CKPT_SHARDS_FETCHED = "ckpt.shards_fetched"
CKPT_SHARDS_RESUMED = "ckpt.shards_resumed"
CKPT_SHARDS_SERVED = "ckpt.shards_served"
CKPT_VERIFY_FAILURES = "ckpt.verify_failures"
EXPERT_ANNOUNCES = "expert.announces"
EXPERT_BYTES_SERVED = "expert.bytes_served"
EXPERT_COMPUTE = "expert.compute"
EXPERT_LOAD_EWMA = "expert.load_ewma"
EXPERT_REQUESTS = "expert.requests"
EXPERT_TOKENS = "expert.tokens"
FAULT_APPLIED = "fault.applied"
FAULT_INJECTED = "fault.injected"
FAULTS_APPLIED = "faults.applied"
FAULTS_INJECTED = "faults.injected"
LEDGER_CLAIM = "ledger.claim"
LEDGER_CLAIMS = "ledger.claims"
LEDGER_DISCREPANCIES = "ledger.discrepancies"
LEDGER_RECEIPT = "ledger.receipt"
LEDGER_RECEIPTS = "ledger.receipts"
LINK_STATS = "link.stats"
METRICS_MALFORMED_RECORDS = "metrics.malformed_records"
MM_FORM_GROUP = "mm.form_group"
MM_JOIN_SERVE = "mm.join.serve"
MM_JOIN_FAILED = "mm.join_failed"
MM_JOIN_FAILURES = "mm.join_failures"
MM_LEADER_ABANDONED = "mm.leader_abandoned"
MM_LEADER_CHANGES = "mm.leader_changes"
MM_LEADER_DISSOLVED = "mm.leader_dissolved"
MM_ROUNDS_ABORTED = "mm.rounds_aborted"
MM_ROUNDS_ATTEMPTED = "mm.rounds_attempted"
MM_ROUNDS_FORMED = "mm.rounds_formed"
NET_BYTES_IN = "net.bytes_in"
NET_BYTES_OUT = "net.bytes_out"
OPT_BOUNDARIES = "opt.boundaries"
OPT_CATCH_UP = "opt.catch_up"
OPT_CATCH_UPS = "opt.catch_ups"
OPT_D2H_BYTES = "opt.d2h_bytes"
OPT_D2H_EXPOSED_S = "opt.d2h_exposed_s"
OPT_D2H_STREAM = "opt.d2h_stream"
OPT_D2H_WAIT_S = "opt.d2h_wait_s"
OPT_EF_RESIDUAL_NORM = "opt.ef_residual_norm"
OPT_GATE_ENGAGED = "opt.gate_engaged"
OPT_GLOBAL_STEP = "opt.global_step"
OPT_GRADS_APPLIED = "opt.grads_applied"
OPT_GRADS_DROPPED = "opt.grads_dropped"
OPT_NAN_ROLLBACK = "opt.nan_rollback"
OPT_NAN_ROLLBACKS = "opt.nan_rollbacks"
OPT_OVERLAP_APPLIED = "opt.overlap_applied"
OPT_OVERLAP_EFFICIENCY = "opt.overlap_efficiency"
OPT_OVERLAP_EXPOSED_S = "opt.overlap_exposed_s"
OPT_OVERLAP_FAILED = "opt.overlap_failed"
OPT_OVERLAP_HIDDEN_S = "opt.overlap_hidden_s"
OPT_OVERLAP_LAUNCHED = "opt.overlap_launched"
OPT_OVERLAP_LEDGER = "opt.overlap_ledger"
OPT_WEIGHT_DECISION = "opt.weight_decision"
OPT_WEIGHT_SCALE = "opt.weight_scale"
PEER_ENDPOINT = "peer.endpoint"
PLAN_SYNC_RETRIES = "plan_sync.retries"
PLAN_SYNC_RETRY = "plan_sync.retry"
RPC_CLIENT_CALLS = "rpc.client.calls"
RPC_CLIENT_FAILURE = "rpc.client.failure"
RPC_CLIENT_FAILURES = "rpc.client.failures"
RPC_CLIENT_REMOTE_ERRORS = "rpc.client.remote_errors"
RPC_CONN_LOST = "rpc.conn_lost"
RPC_CONNS_LOST = "rpc.conns_lost"
RPC_SERVER_ERRORS = "rpc.server.errors"
RPC_SERVER_REQUESTS = "rpc.server.requests"
RUN_CONFIG = "run.config"
SERVE_FALL_THROUGH = "serve.fall_through"
SERVE_HEDGES = "serve.hedges"
SERVE_HOST_FAILURE = "serve.host_failure"
SERVE_KNOWN_EXPERTS = "serve.known_experts"
SERVE_OK = "serve.ok"
SERVE_REFRESHES = "serve.refreshes"
SERVE_REJECT = "serve.reject"
SERVE_REJECTED = "serve.rejected"
SERVE_REQUEST = "serve.request"
SERVE_REQUESTS = "serve.requests"
SERVE_REROUTE = "serve.reroute"
SERVE_REROUTED = "serve.rerouted"
SERVE_RETRIES = "serve.retries"
SERVE_TOKENS = "serve.tokens"
STATE_SERVE = "state.serve"
STATE_SERVED = "state.served"
STATE_SERVED_BYTES = "state.served_bytes"
STATE_SYNC_ATTEMPTS = "state_sync.attempts"
STATE_SYNC_CHECKSUM_FAILURE = "state_sync.checksum_failure"
STATE_SYNC_CHECKSUM_FAILURES = "state_sync.checksum_failures"
STATE_SYNC_FAILED = "state_sync.failed"
STATE_SYNC_FAILURES = "state_sync.failures"
STATE_SYNC_OK = "state_sync.ok"
STATE_SYNC_RETRIES = "state_sync.retries"
STATE_SYNC_RETRY = "state_sync.retry"
STEP_MFU = "step.mfu"
STEP_PHASE = "step.phase"
STEP_PHASE_AVG_WIRE = "step.phase.avg_wire"
STEP_PHASE_FWD_BWD = "step.phase.fwd_bwd"
STEP_RECORD = "step.record"
STEP_SAMPLES_PER_SEC = "step.samples_per_sec"
STEP_WALL = "step.wall"
WATCH_ACTUATION = "watch.actuation"
WATCH_ACTUATIONS = "watch.actuations"
WATCH_INCIDENT = "watch.incident"
WATCH_LEDGER = "watch.ledger"
WATCH_ROLLBACK = "watch.rollback"
WATCH_ROLLBACKS = "watch.rollbacks"

COUNTERS = frozenset({
    "allreduce.bytes_received",
    "allreduce.bytes_sent",
    "allreduce.chunks_received",
    "allreduce.chunks_sent",
    "allreduce.failures",
    "allreduce.rounds",
    "allreduce.stragglers",
    "avg.bytes_saved",
    "avg.topology.fallbacks",
    "avg.topology.replans",
    "avg.topology.rounds",
    "ckpt.fetch_failures",
    "ckpt.fetch_retries",
    "ckpt.manifests_written",
    "ckpt.restore_failures",
    "ckpt.restores",
    "ckpt.shard_bytes_fetched",
    "ckpt.shard_bytes_served",
    "ckpt.shards_fetched",
    "ckpt.shards_resumed",
    "ckpt.shards_served",
    "ckpt.verify_failures",
    "expert.announces",
    "expert.bytes_served",
    "expert.requests",
    "expert.tokens",
    "faults.applied",
    "faults.injected",
    "ledger.claims",
    "ledger.discrepancies",
    "ledger.receipts",
    "metrics.malformed_records",
    "mm.join_failures",
    "mm.leader_changes",
    "mm.rounds_aborted",
    "mm.rounds_attempted",
    "mm.rounds_formed",
    "net.bytes_in",
    "net.bytes_out",
    "opt.boundaries",
    "opt.catch_ups",
    "opt.d2h_bytes",
    "opt.d2h_exposed_s",
    "opt.gate_engaged",
    "opt.grads_applied",
    "opt.grads_dropped",
    "opt.nan_rollbacks",
    "opt.overlap_applied",
    "opt.overlap_exposed_s",
    "opt.overlap_failed",
    "opt.overlap_hidden_s",
    "opt.overlap_launched",
    "plan_sync.retries",
    "rpc.client.calls",
    "rpc.client.failures",
    "rpc.client.remote_errors",
    "rpc.conns_lost",
    "rpc.server.errors",
    "rpc.server.requests",
    "serve.fall_through",
    "serve.hedges",
    "serve.ok",
    "serve.refreshes",
    "serve.rejected",
    "serve.requests",
    "serve.rerouted",
    "serve.retries",
    "serve.tokens",
    "state.served",
    "state.served_bytes",
    "state_sync.attempts",
    "state_sync.checksum_failures",
    "state_sync.failures",
    "state_sync.ok",
    "state_sync.retries",
    "watch.actuations",
    "watch.rollbacks",
})
GAUGES = frozenset({
    "expert.load_ewma",
    "opt.ef_residual_norm",
    "opt.overlap_efficiency",
    "opt.weight_scale",
    "serve.known_experts",
    "step.mfu",
    "step.samples_per_sec",
})
HISTOGRAMS = frozenset({
    "allreduce.chunk_latency_s",
    "allreduce.round",
    "avg.round",
    "ckpt.manifest.serve",
    "ckpt.provider_goodput",
    "ckpt.restore",
    "ckpt.shard.serve",
    "expert.compute",
    "mm.form_group",
    "mm.join.serve",
    "opt.d2h_wait_s",
    "serve.request",
    "state.serve",
    "step.phase.avg_wire",
    "step.phase.fwd_bwd",
    "step.wall",
})
EVENTS = frozenset({
    "allreduce.link",
    "allreduce.round",
    "allreduce.stragglers",
    "avg.round",
    "avg.topology.fallback",
    "avg.topology.plan",
    "avg.topology.replan",
    "avg.topology.round",
    "ckpt.manifest.serve",
    "ckpt.manifest_written",
    "ckpt.restore",
    "ckpt.shard.serve",
    "ckpt.shard_fetch_failed",
    "ckpt.shard_verify_failure",
    "expert.compute",
    "fault.applied",
    "fault.injected",
    "ledger.claim",
    "ledger.receipt",
    "link.stats",
    "mm.form_group",
    "mm.join.serve",
    "mm.join_failed",
    "mm.leader_abandoned",
    "mm.leader_dissolved",
    "opt.catch_up",
    "opt.d2h_stream",
    "opt.global_step",
    "opt.grads_dropped",
    "opt.nan_rollback",
    "opt.overlap_applied",
    "opt.overlap_failed",
    "opt.overlap_launched",
    "opt.overlap_ledger",
    "opt.weight_decision",
    "peer.endpoint",
    "plan_sync.retry",
    "rpc.client.failure",
    "rpc.conn_lost",
    "run.config",
    "serve.fall_through",
    "serve.host_failure",
    "serve.reject",
    "serve.request",
    "serve.reroute",
    "state.serve",
    "state_sync.checksum_failure",
    "state_sync.failed",
    "state_sync.ok",
    "state_sync.retry",
    "step.phase",
    "step.record",
    "watch.actuation",
    "watch.incident",
    "watch.ledger",
    "watch.rollback",
})
SPANS = frozenset({
    "allreduce.round",
    "avg.round",
    "ckpt.manifest.serve",
    "ckpt.restore",
    "ckpt.shard.serve",
    "expert.compute",
    "mm.form_group",
    "mm.join.serve",
    "serve.request",
    "state.serve",
})
EMITTED = COUNTERS | GAUGES | HISTOGRAMS | EVENTS

# declared dynamic-name families (emit-site pragmas)
EMITTED_PREFIXES = (
    "link.",
    "perf.",
    "step.phase.",
)

# how histograms flatten onto the metrics-bus snapshot
SNAPSHOT_SUFFIXES = (".count", ".mean", ".max", ".min")

def known_key(key: str) -> bool:
    """True when ``key`` is a name some instrumented site emits: exact,
    under a declared dynamic prefix, or a snapshot-flattened histogram
    field (``<histogram>.mean`` etc)."""
    if key in EMITTED:
        return True
    if key.startswith(EMITTED_PREFIXES):
        return True
    for suffix in SNAPSHOT_SUFFIXES:
        if key.endswith(suffix):
            base = key[: -len(suffix)]
            if base in HISTOGRAMS or base.startswith(EMITTED_PREFIXES):
                return True
    return False

