"""Live swarm watchdog: streaming anomaly detection over the health fold.

Every diagnostic tool before this one was post-hoc: the coordinator folds
``swarm_health`` records into a JSONL nobody evaluates until a human runs
``runlog_summary``. This module closes that gap. ``SwarmWatch`` consumes
the ORDERED sequence of swarm-health records — live, inline in the
coordinator's fold loop (roles/coordinator.py), or post-hoc over any
coordinator JSONL (tools/swarm_watch.py, ``runlog_summary --incidents``) —
through the exact same code path, so a replay of the dumped JSONL
reproduces the live incident timeline bit-for-bit.

Design:

- **Rolling robust baselines.** Every watched metric (swarm samples/sec,
  round-wall p50/p95, formation p95, per-directed-link RTT/goodput,
  per-peer step-phase walls, mfu, overlap efficiency) keeps a bounded
  window of recent per-fold values; the center is the median, the spread a
  MAD floor — one GC pause cannot rewrite the baseline, and a deterministic
  simulator run (spread ~0) still judges sharply.
- **Windowed, not cumulative.** Health records carry cumulative histogram
  means; consecutive folds' ``(count, mean)`` pairs recover the per-window
  mean (``(c2*m2 - c1*m1) / (c2 - c1)``), so a straggler that turns on at
  fold k is fully visible at fold k+1 instead of diluted into a lifetime
  average. Records without counts (older peers) degrade to cumulative
  means — reported in ``coverage``, never guessed around.
- **Hysteresis.** A detector opens after ``open_after`` consecutive bad
  folds and closes only after ``close_after`` consecutive folds back
  within ``close_deviation`` of baseline; the band between the open and
  close thresholds counts toward neither, so incidents cannot flap.
- **Root-cause suppression.** Detectors run most-specific-first (churn →
  links → peers → swarm). While a specific incident is open, swarm-level
  badness (throughput down, round wall up, rule rates over threshold)
  records as an ``effect`` on it instead of opening a duplicate — one
  degraded link yields ONE incident whose effects list the collateral.
- **Attribution chain.** Every incident ends in something a human can act
  on, reusing the existing ladder: the offending peer and/or directed link
  (topology fold, PR 6), the dominant step phase (PR 8's recorder keys),
  and the trace id of a representative slow round (resolvable by
  ``runlog_summary --trace``).
- **Rules shared with the health fold.** The rule detectors apply
  ``telemetry/health.RULE_THRESHOLDS`` via ``verdict_from_rates`` — the
  ``--health`` verdict header and the watchdog cannot disagree.
- **Twin-backed retuning (ROADMAP item 4, closed loop).** A sustained
  swarm throughput regression marks itself ``retune_eligible``;
  ``twin_recommendation`` then fits a TwinModel from the run's own logs
  (``twin/fit.py``), validates it against its own recording, runs a
  BOUNDED sweep and attaches the recommended config + predicted
  samples/sec + fidelity-bounded interval. Runs with insufficient
  telemetry report ``no_recommendation: <reason>`` instead of guessing.
- **Guard-railed actuation.** A recommendation is no longer the end of the
  loop: ``ActuationGuard`` (below) lets the coordinator (or the simulator's
  closed-loop scenario) APPLY it under hard rails — per-actuation change
  bound, one actuation under observation at a time, a per-plan-epoch
  budget, and automatic rollback when the post-change throughput regresses
  past the pre-change level. Every actuation and rollback lands on the
  incident's ``effects`` list and as ``watch.actuation`` /
  ``watch.rollback`` events, so ``runlog_summary --incidents`` /
  ``swarm_watch`` audit exactly what the loop did. Operators opt out with
  ``--coordinator.actuate_retune false`` (docs/fleet.md "closed-loop
  operations").
"""
from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

from dedloc_tpu.telemetry.health import (
    RULE_THRESHOLDS,
    derive_rates,
    verdict_from_rates,
)
from dedloc_tpu.telemetry.registry import trace_id_for
from dedloc_tpu.utils.logging import get_logger
from dedloc_tpu.utils.stats import median, percentile

logger = get_logger(__name__)

# phases whose inflation points at the WIRE, not this peer's compute — a
# per-peer deviation in one of these while a link incident is open on the
# same peer is that incident's collateral, not a second root cause
_WIRE_PHASES = frozenset({"avg_wire", "collab", "data_wait"})

# incident kinds that name a specific subject; swarm-level badness defers
# to any open incident of these kinds (root-cause suppression)
_SPECIFIC_KINDS = frozenset(
    {"link_degraded", "uplink_degraded", "peer_degraded", "churn_wave",
     "peer_flapping"}
)

# an open incident of these kinds claims further swarm-level badness as an
# effect: one root cause, one incident, however many metrics it drags down
_ROOT_KINDS = _SPECIFIC_KINDS | {"swarm_regression"}

# swarm_regression metrics that constitute a THROUGHPUT regression — the
# retune trigger (a round-wall inflation at fixed workload IS lost
# samples/sec, whether or not the rate detector crossed its own threshold)
_THROUGHPUT_METRICS = frozenset(
    {"samples_per_sec", "round_wall_p50", "round_wall_p95", "mfu"}
)


@dataclass
class WatchConfig:
    """Detector knobs. Defaults are tuned so a deterministic simulator run
    detects a 2x shift within ~2 folds while a production fold cadence
    (30s) tolerates ordinary jitter."""

    baseline_window: int = 16    # folds of history per metric baseline
    warmup_folds: int = 3        # min baseline samples before judging
    open_after: int = 2          # consecutive bad folds to open
    close_after: int = 2         # consecutive good folds to close
    deviation: float = 0.5       # relative deviation that counts as bad
    close_deviation: float = 0.25  # must return within this to close
    mad_k: float = 4.0           # robust-z floor (suppresses noisy fleets)
    critical_low: float = 0.7    # low-direction |dev| >= this: critical
    critical_high: float = 1.5   # high-direction dev >= this: critical
    skew_k: float = 2.0          # peer metric must also be 2x the others
    churn_fraction: float = 0.2  # fraction vanishing in one fold
    churn_min_peers: int = 2     # ...and at least this many peers
    retune_after_folds: int = 3  # sustained throughput folds before retune


class _Baseline:
    """Rolling robust baseline: median center + MAD-floored spread."""

    __slots__ = ("values",)

    def __init__(self, window: int) -> None:
        self.values: Deque[float] = deque(maxlen=window)

    def add(self, v: float) -> None:
        self.values.append(float(v))

    @property
    def n(self) -> int:
        return len(self.values)

    def center(self) -> float:
        return median(list(self.values))

    def spread(self) -> float:
        if len(self.values) < 2:
            return 0.0
        med = self.center()
        return median([abs(v - med) for v in self.values])


class _Detector:
    """One metric's hysteresis state machine. Judgments: "bad" counts
    toward opening, "good" toward closing, the band between counts toward
    neither. The baseline only learns folds that were not bad — an open
    incident must be judged against the PRE-incident baseline, or a slow
    drift would close itself by redefining normal."""

    __slots__ = (
        "key", "subject", "low_bad", "baseline", "bad_streak",
        "good_streak", "incident",
    )

    def __init__(self, key: str, subject: str, low_bad: bool,
                 cfg: WatchConfig) -> None:
        self.key = key
        self.subject = subject
        self.low_bad = low_bad
        self.baseline = _Baseline(cfg.baseline_window)
        self.bad_streak = 0
        self.good_streak = 0
        self.incident: Optional[Dict[str, Any]] = None

    def judge(self, value: float, cfg: WatchConfig) -> Tuple[str, float]:
        """("bad"|"good"|"mid"|"warmup", relative deviation)."""
        if self.baseline.n < cfg.warmup_folds:
            return "warmup", 0.0
        center = self.baseline.center()
        if abs(center) < 1e-12:
            # a zero baseline carries no scale to judge against: "mid"
            # lets the window learn the metric's real level instead of
            # branding any nonzero value an infinite deviation (a
            # permanently-critical incident whose JSON is unparseable)
            return "mid", 0.0
        dev = (value - center) / abs(center)
        directional = -dev if self.low_bad else dev
        # robust-z floor: on a noisy fleet the MAD grows and absorbs
        # ordinary jitter; on a deterministic replay it collapses and the
        # 2%-of-center floor keeps the division sane
        spread_floor = max(self.baseline.spread(), 0.02 * abs(center))
        z = abs(value - center) / spread_floor
        if directional >= cfg.deviation and z >= cfg.mad_k:
            return "bad", dev
        if abs(dev) <= cfg.close_deviation:
            return "good", dev
        return "mid", dev


def _severity(dev: float, low_bad: bool, cfg: WatchConfig) -> str:
    if low_bad:
        return "critical" if -dev >= cfg.critical_low else "warn"
    return "critical" if dev >= cfg.critical_high else "warn"


def _windowed(prev: Optional[Tuple[float, float]],
              cur: Optional[Tuple[float, float]]) -> Optional[float]:
    """Per-window mean from two cumulative (count, mean) observations.
    None when there is nothing new to judge this window."""
    if cur is None:
        return None
    c2, m2 = cur
    if prev is None:
        return m2 if c2 > 0 else None
    c1, m1 = prev
    if c2 > c1:
        return (c2 * m2 - c1 * m1) / (c2 - c1)
    return None


class SwarmWatch:
    """The streaming watchdog. Feed it swarm-health records in order
    (``observe_health``), read ``incidents`` / ``summary()``. Pure
    computation — no clocks, no I/O — so the same instance runs inline in
    the coordinator loop, inside the virtual-time simulator, and over a
    replayed JSONL with identical results."""

    def __init__(self, config: Optional[WatchConfig] = None) -> None:
        self.cfg = config or WatchConfig()
        self.fold = -1
        self.incidents: List[Dict[str, Any]] = []
        self._detectors: Dict[Tuple[str, str], _Detector] = {}
        self._prev_health: Optional[Dict] = None
        self._prev_t: Optional[float] = None
        self._prev_peer_stats: Dict[str, Dict[str, Tuple[float, float]]] = {}
        self._prev_labels: set = set()
        self._gone_peers: set = set()
        self._churn_detector: Optional[Dict[str, Any]] = None
        self._churn_good_streak = 0
        self._seen_throughput = False
        self._recent_rounds: Deque[Dict[str, Any]] = deque(maxlen=64)
        self.coverage: Dict[str, Any] = {
            "folds": 0, "folds_with_topology": 0, "folds_with_rounds": 0,
            "folds_with_phases": 0, "folds_with_counts": 0,
            "folds_with_time": 0, "peers_seen": 0,
        }
        self._notes: set = set()
        self.last_verdict: Optional[Dict[str, str]] = None

    # ------------------------------------------------------------ plumbing

    def _detector(self, key: str, subject: str, low_bad: bool) -> _Detector:
        d = self._detectors.get((key, subject))
        if d is None:
            d = self._detectors[(key, subject)] = _Detector(
                key, subject, low_bad, self.cfg
            )
        return d

    def open_incidents(self) -> List[Dict[str, Any]]:
        return [i for i in self.incidents if i["status"] == "open"]

    def _open(self, detector: Optional[_Detector], *, kind: str,
              metric: str, subject: str, observed: Optional[float],
              baseline: Optional[float], deviation: Optional[float],
              severity: str, t: Optional[float], step: Optional[int],
              **attribution: Any) -> Dict[str, Any]:
        incident: Dict[str, Any] = {
            "id": f"inc-{len(self.incidents):04d}",
            "kind": kind,
            "metric": metric,
            "subject": subject,
            "severity": severity,
            "status": "open",
            "opened_fold": self.fold,
            "opened_t": t,
            "opened_step": step,
            "closed_fold": None,
            "closed_t": None,
            "observed": observed,
            "baseline": baseline,
            "deviation": (
                round(deviation, 4) if deviation is not None else None
            ),
            "effects": [],
        }
        incident.update(attribution)
        self.incidents.append(incident)
        if detector is not None:
            detector.incident = incident
        return incident

    def _close(self, incident: Dict[str, Any], t: Optional[float]) -> None:
        incident["status"] = "closed"
        incident["closed_fold"] = self.fold
        incident["closed_t"] = t

    def _effect(self, incident: Dict[str, Any], metric: str,
                deviation: Optional[float]) -> None:
        """Record swarm-level collateral on a specific open incident, once
        per metric (the first — worst-to-detect — observation wins)."""
        if any(e["metric"] == metric for e in incident["effects"]):
            return
        incident["effects"].append({
            "metric": metric,
            "deviation": (
                round(deviation, 4) if deviation is not None else None
            ),
            "fold": self.fold,
        })

    def _refresh_representative(self, incident: Dict[str, Any]) -> None:
        """Attach (and, while the incident stays open, keep refreshing) the
        representative slow round: the slowest recently-seen round —
        restricted to the attributed peer's member spans when it recorded
        any, else swarm-wide. The trace id comes off the round record when
        the fold carried one, else derives deterministically from the
        round id (``registry.trace_id_for``: every member of a round seeds
        the same id, so the derived id resolves against per-peer event
        logs). New folds can bring worse evidence; the slowest wins."""
        peer = incident.get("peer")
        candidates = [
            r for r in self._recent_rounds
            if r.get("dur_s") is not None and r.get("peer") == peer
        ] if peer is not None else []
        if not candidates:
            candidates = [
                r for r in self._recent_rounds if r.get("dur_s") is not None
            ]
        if not candidates:
            return
        worst = max(candidates, key=lambda r: float(r["dur_s"]))
        dur = float(worst["dur_s"])
        current = incident.get("representative_dur_s")
        if current is not None and dur <= current:
            return
        round_id = str(worst.get("round_id", "")) or None
        incident["representative_dur_s"] = round(dur, 6)
        incident["round_id"] = round_id
        incident["trace"] = worst.get("trace") or (
            trace_id_for(round_id) if round_id else None
        )

    # ----------------------------------------------------- detector driver

    def _drive(self, key: str, subject: str, value: Optional[float],
               low_bad: bool, *, kind: str, t: Optional[float],
               step: Optional[int],
               suppress_into: Optional[List[Dict[str, Any]]] = None,
               gate_ok: bool = True,
               attribution: Optional[Dict[str, Any]] = None,
               transitions: Optional[List] = None) -> None:
        """One detector, one fold. ``suppress_into``: open specific
        incidents that claim this metric's badness as an effect instead of
        a new incident. ``gate_ok=False`` vetoes OPENING this fold (e.g.
        the peer-skew gate) without resetting the baseline machinery."""
        if value is None:
            return
        d = self._detector(key, subject, low_bad)
        verdict, dev = d.judge(value, self.cfg)
        bad = verdict == "bad" and gate_ok
        # suppression applies only while THIS detector has no incident of
        # its own: an open incident keeps driving its own lifecycle (and
        # must never absorb its own metric as an "effect")
        if bad and suppress_into and d.incident is None:
            for inc in suppress_into:
                if inc is not d.incident:
                    self._effect(inc, key, dev)
            # learns nothing this fold (the value is anomalous), opens
            # nothing (the root cause is already an incident)
            d.bad_streak = 0
            d.good_streak = 0
            return
        if bad:
            d.bad_streak += 1
            d.good_streak = 0
        elif verdict == "good":
            d.good_streak += 1
            d.bad_streak = 0
        else:
            d.bad_streak = 0
            d.good_streak = 0
        if verdict != "bad":
            # "mid", "good" and warmup folds refine the baseline; bad
            # folds must not teach it the anomaly (judge() never says
            # "bad" during warmup, so warmup always lands here)
            d.baseline.add(value)

        if d.incident is None:
            if bad and d.bad_streak >= self.cfg.open_after:
                incident = self._open(
                    d, kind=kind, metric=key, subject=subject,
                    observed=round(value, 6),
                    baseline=round(d.baseline.center(), 6),
                    deviation=dev,
                    severity=_severity(dev, low_bad, self.cfg),
                    t=t, step=step, **(attribution or {}),
                )
                self._refresh_representative(incident)
                if transitions is not None:
                    transitions.append(
                        {"transition": "open", "incident": incident}
                    )
        else:
            incident = d.incident
            if bad:
                # live update: the current reading and (escalating only)
                # severity track the worst of the incident
                incident["observed"] = round(value, 6)
                incident["deviation"] = round(dev, 4)
                if _severity(dev, low_bad, self.cfg) == "critical":
                    incident["severity"] = "critical"
                self._refresh_representative(incident)
            if d.good_streak >= self.cfg.close_after:
                self._close(incident, t)
                d.incident = None
                if transitions is not None:
                    transitions.append(
                        {"transition": "close", "incident": incident}
                    )

    # ------------------------------------------------------------- folding

    def observe_health(
        self,
        health: Dict[str, Any],
        t: Optional[float] = None,
        step: Optional[int] = None,
        samples_per_sec: Optional[float] = None,
    ) -> List[Dict[str, Any]]:
        """Consume one swarm-health record; returns the fold's incident
        transitions (``[{"transition": "open"|"close", "incident": ...}]``,
        each referencing the LIVE incident dict)."""
        cfg = self.cfg
        self.fold += 1
        cov = self.coverage
        cov["folds"] += 1
        transitions: List[Dict[str, Any]] = []
        peers = [
            p for p in health.get("peers", []) if isinstance(p, dict)
        ]
        labels = {str(p.get("peer")) for p in peers if p.get("peer")}
        cov["peers_seen"] = max(cov["peers_seen"], len(labels))
        if step is None:
            step = health.get("current_step")
        dt = None
        if t is not None and self._prev_t is not None and t > self._prev_t:
            dt = t - self._prev_t
        if t is not None:
            cov["folds_with_time"] += 1

        rounds = health.get("rounds") or []
        if rounds:
            cov["folds_with_rounds"] += 1
            for r in rounds:
                if isinstance(r, dict):
                    self._recent_rounds.append(r)

        # ------------------------------------------------------ churn wave
        # a peer that came back is no longer "gone": it may die again
        # later, and that second death must count
        self._gone_peers -= labels
        lost = (self._prev_labels - labels) - self._gone_peers
        if self._prev_labels:
            threshold = max(
                cfg.churn_min_peers,
                int(cfg.churn_fraction * len(self._prev_labels)),
            )
            if self._churn_detector is None:
                if len(lost) >= threshold:
                    incident = self._open(
                        None, kind="churn_wave", metric="peers_lost",
                        subject="swarm", observed=float(len(lost)),
                        baseline=float(len(self._prev_labels)),
                        deviation=-len(lost) / len(self._prev_labels),
                        severity="critical", t=t, step=step,
                        peers_lost=sorted(lost),
                    )
                    self._refresh_representative(incident)
                    self._churn_detector = incident
                    self._churn_good_streak = 0
                    transitions.append(
                        {"transition": "open", "incident": incident}
                    )
            else:
                incident = self._churn_detector
                if lost:
                    incident["peers_lost"] = sorted(
                        set(incident["peers_lost"]) | lost
                    )
                    incident["observed"] = float(
                        len(incident["peers_lost"])
                    )
                    self._churn_good_streak = 0
                else:
                    self._churn_good_streak += 1
                    if self._churn_good_streak >= cfg.close_after:
                        self._close(incident, t)
                        self._churn_detector = None
                        transitions.append(
                            {"transition": "close", "incident": incident}
                        )
        self._gone_peers |= lost

        # ------------------------------------------------- per-link health
        links: Dict[Tuple[str, str], Dict[str, float]] = {}
        topology = health.get("topology")
        if isinstance(topology, dict):
            cov["folds_with_topology"] += 1
            for link in topology.get("links", []):
                if not isinstance(link, dict):
                    continue
                src = str(link.get("src", "?"))
                dst = str(link.get("dst", link.get("dst_endpoint", "?")))
                links[(src, dst)] = link
        # per-peer windowed stats (needed for link-phase attribution below,
        # so computed before the link detectors run)
        peer_stats: Dict[str, Dict[str, Tuple[float, float]]] = {}
        windowed_phase: Dict[str, Dict[str, float]] = {}
        windowed_round: Dict[str, float] = {}
        windowed_formation: List[float] = []
        any_phases = any_counts = False
        for p in peers:
            label = str(p.get("peer", "?"))
            cur: Dict[str, Tuple[float, float]] = {}
            phases = p.get("phases")
            phase_counts = p.get("phase_counts") or {}
            if isinstance(phases, dict) and phases:
                any_phases = True
                for name, mean in phases.items():
                    count = phase_counts.get(name)
                    if count is not None:
                        any_counts = True
                        cur[f"phase.{name}"] = (float(count), float(mean))
                    else:
                        cur[f"phase.{name}"] = (
                            float(self.fold + 1), float(mean)
                        )
                        self._notes.add(
                            "phase means without sample counts (older "
                            "peers): windowing approximated by fold index"
                        )
            if p.get("round_s") is not None:
                count = p.get("round_count")
                if count is None:
                    count = float(self.fold + 1)
                    self._notes.add(
                        "round means without sample counts (older peers): "
                        "windowing approximated by fold index"
                    )
                cur["round"] = (float(count), float(p["round_s"]))
            if p.get("round_formation_s") is not None:
                count = p.get("round_formation_count")
                if count is None:
                    count = float(self.fold + 1)
                cur["formation"] = (
                    float(count), float(p["round_formation_s"])
                )
            prev = self._prev_peer_stats.get(label, {})
            for key, pair in cur.items():
                w = _windowed(prev.get(key), pair)
                if w is None:
                    continue
                # dedlint: disable=schema-consumed-unknown — "phase." is
                # the fold's OWN per-peer stat namespace (health records),
                # not a telemetry emit name
                if key.startswith("phase."):  # dedlint: disable=schema-consumed-unknown
                    windowed_phase.setdefault(label, {})[
                        key[len("phase."):]
                    ] = w
                elif key == "round":
                    windowed_round[label] = w
                elif key == "formation":
                    windowed_formation.append(w)
            peer_stats[label] = cur
        if any_phases:
            cov["folds_with_phases"] += 1
        if any_counts:
            cov["folds_with_counts"] += 1

        def _phase_attribution(label: str) -> Optional[str]:
            """The peer's most-deviating windowed phase vs its own
            baseline — the 'and WHY' rung of the ladder."""
            best_name, best_dev = None, 0.0
            for name, value in (windowed_phase.get(label) or {}).items():
                d = self._detector(f"peer_phase.{name}", f"peer:{label}",
                                   low_bad=False)
                if d.baseline.n < cfg.warmup_folds:
                    continue
                center = d.baseline.center()
                if center <= 1e-12:
                    continue
                dev = (value - center) / center
                if dev > best_dev:
                    best_name, best_dev = name, dev
            return best_name if best_dev >= cfg.deviation else None

        # a sender's outgoing links share one serialized uplink: when the
        # uplink itself degrades, EVERY outgoing goodput collapses together
        # — that is ONE uplink event, not N link incidents. A link only
        # earns its own incident when it is distinguishably worse than its
        # siblings; the per-src uplink detector (median outgoing goodput)
        # owns the collapse-together case.
        goodput_by_src: Dict[str, Dict[str, float]] = {}
        for (src, dst), link in links.items():
            if link.get("goodput_bps") is not None:
                goodput_by_src.setdefault(src, {})[dst] = float(
                    link["goodput_bps"]
                )
        for (src, dst), link in sorted(links.items()):
            subject = f"link:{src}->{dst}"
            goodput = link.get("goodput_bps")
            if goodput is not None:
                siblings = [
                    g for d, g in goodput_by_src.get(src, {}).items()
                    if d != dst
                ]
                gate_ok = len(siblings) < 2 or float(goodput) <= (
                    0.5 * median(siblings)
                )
                self._drive(
                    "link_goodput", subject, float(goodput), low_bad=True,
                    kind="link_degraded", t=t, step=step, gate_ok=gate_ok,
                    attribution={
                        "peer": src, "link": {"src": src, "dst": dst},
                        "phase": _phase_attribution(src),
                    },
                    transitions=transitions,
                )
            rtt = link.get("rtt_s")
            if rtt is not None:
                self._drive(
                    "link_rtt", subject, float(rtt), low_bad=False,
                    kind="link_degraded", t=t, step=step,
                    attribution={
                        "peer": src, "link": {"src": src, "dst": dst},
                        "phase": _phase_attribution(src),
                    },
                    transitions=transitions,
                )
        uplink_medians = {
            src: median(list(outgoing.values()))
            for src, outgoing in goodput_by_src.items()
        }
        for src, outgoing in sorted(goodput_by_src.items()):
            if len(outgoing) < 3:
                continue  # too few links to call it an uplink property
            # vs-swarm gate (same shape as the peer-phase skew gate): when
            # EVERY peer's uplink collapses together the event is
            # swarm-wide — wire path, config push, provider outage — and
            # belongs to the swarm detectors, not to N uplink incidents
            others = [
                v for other, v in uplink_medians.items() if other != src
            ]
            gate_ok = len(others) < 2 or uplink_medians[src] <= (
                0.5 * median(others)
            )
            self._drive(
                "uplink_goodput", f"uplink:{src}",
                uplink_medians[src], low_bad=True,
                kind="uplink_degraded", t=t, step=step, gate_ok=gate_ok,
                attribution={
                    "peer": src, "phase": _phase_attribution(src),
                },
                transitions=transitions,
            )

        open_link_incidents = [
            i for i in self.open_incidents()
            if i["kind"] in ("link_degraded", "uplink_degraded")
        ]

        # ------------------------------------------------- per-peer health
        for p in peers:
            label = str(p.get("peer", "?"))
            calls = float(p.get("rpc_calls", 0.0))
            lost_conns = float(p.get("conns_lost", 0.0))
            if calls >= 20:
                ratio = lost_conns / calls
                limit = RULE_THRESHOLDS["peer_loss_ratio"]
                self._drive_rule(
                    "peer_loss_ratio", f"peer:{label}", ratio, limit,
                    kind="peer_flapping", t=t, step=step,
                    attribution={"peer": label},
                    transitions=transitions,
                )
            for name, value in sorted(
                (windowed_phase.get(label) or {}).items()
            ):
                # skew gate: the peer must ALSO stand out from the rest of
                # the swarm right now — a global slowdown is a swarm
                # incident, not N peer incidents
                others = [
                    v[name] for other, v in windowed_phase.items()
                    if other != label and name in v
                ]
                gate_ok = True
                if len(others) >= 2:
                    gate_ok = value >= cfg.skew_k * max(
                        median(others), 1e-12
                    )
                suppress = [
                    i for i in open_link_incidents
                    if i.get("peer") == label and name in _WIRE_PHASES
                ]
                self._drive(
                    f"peer_phase.{name}", f"peer:{label}", value,
                    low_bad=False, kind="peer_degraded", t=t, step=step,
                    gate_ok=gate_ok, suppress_into=suppress,
                    attribution={"peer": label, "phase": name},
                    transitions=transitions,
                )

        def _open_roots() -> List[Dict[str, Any]]:
            """Open incidents that claim swarm-level badness as effects —
            recomputed per metric so the first swarm incident a fold opens
            absorbs the fold's remaining swarm-level deviations."""
            return [
                i for i in self.open_incidents()
                if i["kind"] in _ROOT_KINDS
            ]

        # --------------------------------------------------- swarm metrics
        if samples_per_sec is None:
            reported = [
                float(p["samples_per_second"]) for p in peers
                if p.get("samples_per_second") is not None
            ]
            if reported:
                total = sum(reported)
                if total > 0:
                    samples_per_sec = total
                elif self._seen_throughput:
                    # a measured all-zero window once the swarm has ever
                    # reported throughput is a TOTAL collapse — judged at
                    # −100%, not skipped as missing data; before that,
                    # zeros are first-fold placeholders (no rate window
                    # existed yet)
                    samples_per_sec = 0.0
        if samples_per_sec is not None and samples_per_sec > 0:
            self._seen_throughput = True

        round_walls: List[float] = []
        if rounds:
            round_walls = [
                float(r["dur_s"]) for r in rounds
                if isinstance(r, dict) and r.get("dur_s") is not None
                and r.get("ok") is not False
            ]
        elif windowed_round:
            round_walls = sorted(windowed_round.values())
            self._notes.add(
                "no round summaries in folds: round-wall percentiles "
                "derived from per-peer windowed means"
            )

        def _swarm_peer_attribution() -> Dict[str, Any]:
            """Best-effort peer/link/phase for a swarm-level incident: the
            peer whose windowed round wall most exceeds the others."""
            out: Dict[str, Any] = {}
            if len(windowed_round) >= 2:
                worst = max(windowed_round, key=windowed_round.get)
                rest = [
                    v for k, v in windowed_round.items() if k != worst
                ]
                if windowed_round[worst] >= cfg.skew_k * max(
                    median(rest), 1e-12
                ):
                    out["peer"] = worst
                    out["phase"] = _phase_attribution(worst)
            if "peer" not in out and health.get("straggler"):
                out["peer"] = health["straggler"]
            return out

        swarm_metrics: List[Tuple[str, Optional[float], bool]] = [
            ("samples_per_sec", samples_per_sec, True),
            (
                "round_wall_p50",
                percentile(round_walls, 0.50) if round_walls else None,
                False,
            ),
            (
                "round_wall_p95",
                percentile(round_walls, 0.95) if round_walls else None,
                False,
            ),
            (
                "formation_p95",
                percentile(windowed_formation, 0.95)
                if windowed_formation else None,
                False,
            ),
        ]
        mfus = [float(p["mfu"]) for p in peers if p.get("mfu") is not None]
        if mfus:
            swarm_metrics.append(("mfu", sum(mfus) / len(mfus), True))
        effs = [
            float(p["overlap_efficiency"]) for p in peers
            if p.get("overlap_efficiency") is not None
        ]
        if effs:
            swarm_metrics.append(
                ("overlap_efficiency", sum(effs) / len(effs), True)
            )
        for key, value, low_bad in swarm_metrics:
            self._drive(
                key, "swarm", value, low_bad=low_bad,
                kind="swarm_regression", t=t, step=step,
                suppress_into=_open_roots(),
                attribution=_swarm_peer_attribution(),
                transitions=transitions,
            )

        # ------------------------------------------------------ rule rates
        rates = health.get("derived")
        if not isinstance(rates, dict) or self._prev_health is not None:
            # recompute windowed against the previous fold when we can —
            # the record's own "derived" is cumulative-by-construction
            rates = derive_rates(health, prev=self._prev_health, dt_s=dt)
        for key in ("round_abort_rate", "join_failure_rate",
                    "conns_lost_per_min"):
            value = rates.get(key)
            if value is None:
                continue
            self._drive_rule(
                key, "swarm", float(value), RULE_THRESHOLDS[key],
                kind="rule", t=t, step=step,
                suppress_into=_open_roots(),
                transitions=transitions,
            )
        self.last_verdict = dict(health.get("verdict") or {})
        if not self.last_verdict:
            status, reason = verdict_from_rates(
                rates, health.get("straggler")
            )
            self.last_verdict = {"status": status, "reason": reason}

        # retune eligibility: a sustained swarm-level throughput regression
        # (directly, or as the absorbed effect of the fold's root incident)
        for incident in self.open_incidents():
            throughput_hit = incident["kind"] == "swarm_regression" and (
                incident["metric"] in _THROUGHPUT_METRICS
                or any(
                    e["metric"] in _THROUGHPUT_METRICS
                    for e in incident["effects"]
                )
            )
            if (
                throughput_hit
                and not incident.get("retune_eligible")
                and self.fold - incident["opened_fold"]
                >= cfg.retune_after_folds - 1
            ):
                incident["retune_eligible"] = True
                transitions.append(
                    {"transition": "retune_eligible", "incident": incident}
                )

        self._prev_health = health
        self._prev_t = t if t is not None else self._prev_t
        self._prev_peer_stats = peer_stats
        self._prev_labels = labels
        return transitions

    def _drive_rule(self, key: str, subject: str, value: float,
                    limit: float, *, kind: str, t: Optional[float],
                    step: Optional[int],
                    suppress_into: Optional[List[Dict[str, Any]]] = None,
                    attribution: Optional[Dict[str, Any]] = None,
                    transitions: Optional[List] = None) -> None:
        """Absolute-threshold rule with the same hysteresis machinery:
        bad above ``limit``, good below half of it."""
        d = self._detector(f"rule.{key}", subject, low_bad=False)
        bad = value > limit
        good = value <= 0.5 * limit
        if bad and suppress_into:
            for inc in suppress_into:
                self._effect(inc, key, value / limit - 1.0)
            d.bad_streak = d.good_streak = 0
            return
        if bad:
            d.bad_streak += 1
            d.good_streak = 0
        elif good:
            d.good_streak += 1
            d.bad_streak = 0
        else:
            d.bad_streak = d.good_streak = 0
        if d.incident is None:
            if bad and d.bad_streak >= self.cfg.open_after:
                incident = self._open(
                    d, kind=kind, metric=key, subject=subject,
                    observed=round(value, 6), baseline=limit,
                    deviation=round(value / limit - 1.0, 4),
                    severity=(
                        "critical" if value > 2.0 * limit else "warn"
                    ),
                    t=t, step=step, **(attribution or {}),
                )
                self._refresh_representative(incident)
                if transitions is not None:
                    transitions.append(
                        {"transition": "open", "incident": incident}
                    )
        else:
            incident = d.incident
            if bad:
                incident["observed"] = round(value, 6)
                incident["deviation"] = round(value / limit - 1.0, 4)
            if d.good_streak >= self.cfg.close_after:
                self._close(incident, t)
                d.incident = None
                if transitions is not None:
                    transitions.append(
                        {"transition": "close", "incident": incident}
                    )

    # ------------------------------------------------------------- summary

    def summary(self) -> Dict[str, Any]:
        """The watchdog's machine-readable state: incidents (open first,
        then by opening fold), coverage — every blind spot the input had is
        NAMED, never silently absorbed — and the latest shared verdict."""
        cov = dict(self.coverage)
        notes = set(self._notes)
        if cov["folds"]:
            if not cov["folds_with_topology"]:
                notes.add(
                    "no topology in any fold (pre-link peers or telemetry "
                    "off): link detectors idle"
                )
            if not cov["folds_with_phases"]:
                notes.add(
                    "no step-phase data in any fold (pre-recorder peers): "
                    "phase attribution unavailable"
                )
            if not cov["folds_with_rounds"]:
                notes.add(
                    "no round summaries in any fold: representative-trace "
                    "attribution unavailable"
                )
            if not cov["folds_with_time"]:
                notes.add(
                    "no fold timestamps: per-minute rule rates skipped"
                )
        cov["notes"] = sorted(notes)
        ordered = sorted(
            self.incidents,
            key=lambda i: (i["status"] != "open", i["opened_fold"]),
        )
        return {
            "view": "watch",
            "folds": cov["folds"],
            "incidents": ordered,
            "open": len(self.open_incidents()),
            "coverage": cov,
            "verdict": self.last_verdict,
        }


# ---------------------------------------------------------------------------
# Post-hoc replay: the SAME watchdog over loaded JSONL rows.
# ---------------------------------------------------------------------------


def watch_rows(rows: List[Dict[str, Any]],
               config: Optional[WatchConfig] = None) -> SwarmWatch:
    """Replay a coordinator metrics JSONL (already loaded, e.g. via the
    shared ``load_jsonl_rows`` loader) through a fresh ``SwarmWatch``.
    Rows without a ``swarm_health`` record are skipped — they are the
    throughput aggregates and stray telemetry the same file carries."""
    watch = SwarmWatch(config)
    for row in rows:
        if not isinstance(row, dict):
            continue
        health = row.get("swarm_health")
        if not isinstance(health, dict):
            continue
        t = row.get("time")
        watch.observe_health(
            health,
            t=float(t) if t is not None else None,
            step=row.get("step"),
            samples_per_sec=row.get("samples_per_second"),
        )
    return watch


# ---------------------------------------------------------------------------
# Twin-backed retuning (ROADMAP item 4's closed loop): recommendation fit +
# the guard-railed actuation machinery that applies it (ISSUE 16).
# ---------------------------------------------------------------------------

# bounded by construction: the sweep the watchdog runs on an incident is a
# handful of replays, not the full tools/twin_sweep.py grid
RETUNE_MAX_CONFIGS = 4
RETUNE_REPLAY_ROUNDS = 2


def twin_recommendation(
    rows: List[Dict[str, Any]],
    seed: int = 0,
    grid: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Fit a TwinModel from the run's own telemetry rows, validate it
    against its own recording, sweep a small config grid and return either
    a recommendation (``config`` + ``predicted_samples_per_sec`` +
    fidelity-bounded ``interval``) or ``{"no_recommendation": <reason>}``.
    Never raises on bad input — an incident with no usable telemetry gets
    a reason, not a guess (and never a crash in the coordinator loop)."""
    from dedloc_tpu.twin.fit import fit_twin
    from dedloc_tpu.twin.replay import fidelity_report, replay_twin

    try:
        model = fit_twin(rows)
    except ValueError as e:
        return {"no_recommendation": f"twin not fittable: {e}"}
    cov = model.coverage
    if cov.get("links_with_bandwidth", 0) == 0:
        return {"no_recommendation": (
            "insufficient coverage: no link bandwidth was measured "
            "(pre-link-schema peers or telemetry off)"
        )}
    if cov.get("peers_with_compute", 0) == 0:
        return {"no_recommendation": (
            "insufficient coverage: no per-peer compute was measured "
            "(pre-step-recorder peers)"
        )}
    if not model.workload.get("rounds"):
        return {"no_recommendation": (
            "insufficient coverage: no recorded rounds — the workload "
            "shape is unknown"
        )}
    try:
        fidelity = fidelity_report(model, seed=seed)
    except Exception as e:  # noqa: BLE001 — a replay failure is a reason
        return {"no_recommendation": f"twin replay failed: {e!r}"}
    bound = fidelity.get("sweep_error_bound")
    if bound is None:
        return {"no_recommendation": (
            "twin unvalidated: the recording carries no observed rounds "
            "to bound the prediction error"
        )}
    if bound > 1.0:
        # a twin that misses its own recording by over 100% predicts
        # nothing — saying so beats recommending from noise
        return {"no_recommendation": (
            f"twin fidelity insufficient (error bound "
            f"±{bound * 100.0:.0f}% against its own recording)"
        )}
    if grid is None:
        chunk_rec = int(
            (model.workload.get("chunk_bytes") or 24576) // 4
        )
        span_elems = max(
            chunk_rec, int((model.workload.get("span_bytes") or 98304) // 4)
        )
        grid = [
            {"chunk_size": chunk_rec, "overlap": False},
            {"chunk_size": min(chunk_rec * 4, span_elems),
             "overlap": False},
            {"chunk_size": chunk_rec, "overlap": True},
            {"chunk_size": min(chunk_rec * 4, span_elems),
             "overlap": True},
        ]
    grid = grid[:RETUNE_MAX_CONFIGS]
    results = []
    for config in grid:
        overrides = dict(config)
        overrides["rounds"] = RETUNE_REPLAY_ROUNDS
        try:
            report = replay_twin(model, overrides=overrides, seed=seed)
        except Exception as e:  # noqa: BLE001 — a failed config reports
            results.append({"config": config, "error": repr(e)})
            continue
        results.append({
            "config": config,
            "samples_per_sec": report.get("samples_per_sec"),
            "round_wall_p50_s": report.get("round_wall_p50_s"),
        })
    ok = [r for r in results if r.get("samples_per_sec")]
    if not ok:
        return {"no_recommendation": (
            "no sweep config produced a throughput prediction"
        ), "configs": results}
    best = max(ok, key=lambda r: r["samples_per_sec"])
    predicted = float(best["samples_per_sec"])
    return {
        "config": best["config"],
        "predicted_samples_per_sec": round(predicted, 3),
        "interval": [
            round(max(0.0, predicted * (1.0 - bound)), 3),
            round(predicted * (1.0 + bound), 3),
        ],
        "fidelity_bound": bound,
        "configs_evaluated": len(results),
        "observed_samples_per_sec": model.observed.get("samples_per_sec"),
    }


# actuation-eligible config keys (the twin sweep's grid keys — see the
# default grid in twin_recommendation): anything else a recommendation
# carries is reported but never applied
ACTUATION_KEYS = ("chunk_size", "overlap")


@dataclass
class ActuationConfig:
    """Guard-rail knobs for applying a twin recommendation (docs/fleet.md
    "closed-loop operations"). Defaults are deliberately conservative: one
    bounded change at a time, judged within a handful of folds."""

    # numeric keys move at most this factor from the current value per
    # actuation (a 64x chunk-size jump becomes two guarded 4x–16x steps)
    max_change_factor: float = 4.0
    # folds to let the change take effect before judging it
    settle_folds: int = 1
    # post-settle folds the change must survive to be kept
    observe_folds: int = 3
    # rollback when post-change samples/sec drops below
    # (1 - rollback_margin) x the pre-change level
    rollback_margin: float = 0.1
    # folds between actuations (after a verdict, either way)
    cooldown_folds: int = 4
    # actuations per topology-plan epoch — a re-plan resets the budget
    max_actuations_per_epoch: int = 2


class ActuationGuard:
    """The guard rail between a twin recommendation and the running swarm.

    Pure computation like ``SwarmWatch`` — no clocks, no I/O, fold indices
    come from the caller — so the coordinator's live loop and the
    simulator's virtual-time closed-loop scenario share this one
    implementation. Protocol: ``consider`` clamps a recommendation into an
    applicable delta (or refuses with a reason), the caller applies it and
    calls ``actuate`` (which records the incident effect), then feeds every
    subsequent fold's swarm samples/sec into ``observe`` until a verdict —
    ``"rollback"`` (the caller must re-apply ``record["revert"]`` and
    append the rollback effect via ``rollback_effect``) or ``"kept"``."""

    def __init__(self, config: Optional[ActuationConfig] = None) -> None:
        self.cfg = config or ActuationConfig()
        self.active: Optional[Dict[str, Any]] = None
        self.history: List[Dict[str, Any]] = []
        self._cooldown_until = -1
        self._per_epoch: Dict[int, int] = {}

    def consider(
        self,
        recommendation: Dict[str, Any],
        current_config: Dict[str, Any],
        *,
        fold: int,
        epoch: int = 0,
    ) -> Dict[str, Any]:
        """Clamp ``recommendation["config"]`` against the guard rails.
        Returns ``{"apply": delta, "revert": previous, "clamped": keys}``
        or ``{"refused": reason}`` — never raises."""
        cfg = self.cfg
        if self.active is not None:
            return {"refused": (
                f"actuation {self.active['applied']} from fold "
                f"{self.active['fold']} is still under observation"
            )}
        if fold < self._cooldown_until:
            return {"refused": (
                f"in post-actuation cooldown until fold "
                f"{self._cooldown_until}"
            )}
        if self._per_epoch.get(epoch, 0) >= cfg.max_actuations_per_epoch:
            return {"refused": (
                f"actuation budget exhausted for plan epoch {epoch} "
                f"({cfg.max_actuations_per_epoch} per epoch)"
            )}
        config = recommendation.get("config") or {}
        applied: Dict[str, Any] = {}
        revert: Dict[str, Any] = {}
        clamped: List[str] = []
        for key in ACTUATION_KEYS:
            if key not in config:
                continue
            want, cur = config[key], current_config.get(key)
            if want == cur:
                continue
            if isinstance(want, bool) or isinstance(cur, bool):
                applied[key] = bool(want)
            elif (
                isinstance(want, (int, float))
                and isinstance(cur, (int, float))
                and cur > 0
            ):
                bounded = min(
                    max(float(want), cur / cfg.max_change_factor),
                    cur * cfg.max_change_factor,
                )
                if isinstance(cur, int):
                    bounded = int(round(bounded))
                if bounded != want:
                    clamped.append(key)
                if bounded == cur:
                    continue
                applied[key] = bounded
            else:
                applied[key] = want
            revert[key] = cur
        if not applied:
            return {"refused": (
                "recommended config matches the current config "
                "(nothing to apply within the guard rail)"
            )}
        return {"apply": applied, "revert": revert, "clamped": clamped}

    def actuate(
        self,
        incident: Dict[str, Any],
        applied: Dict[str, Any],
        revert: Dict[str, Any],
        *,
        fold: int,
        baseline_samples_per_sec: Optional[float],
        epoch: int = 0,
        clamped: Tuple[str, ...] = (),
    ) -> Dict[str, Any]:
        """Record a just-applied config delta and start observing it.
        Appends the ``actuation`` effect to the incident and returns the
        live actuation record (also kept in ``history``)."""
        record: Dict[str, Any] = {
            "incident": incident.get("id"),
            "applied": dict(applied),
            "revert": dict(revert),
            "clamped": list(clamped),
            "fold": fold,
            "epoch": epoch,
            "baseline_samples_per_sec": baseline_samples_per_sec,
            "observed": [],
            "verdict": "observing",
        }
        self.active = record
        self.history.append(record)
        self._per_epoch[epoch] = self._per_epoch.get(epoch, 0) + 1
        verdict = "applied"
        if clamped:
            verdict += f" (guard-rail clamped: {', '.join(clamped)})"
        incident.setdefault("effects", []).append({
            "metric": "actuation",
            "deviation": None,
            "fold": fold,
            "applied": dict(applied),
            "verdict": verdict,
        })
        return record

    def observe(self, samples_per_sec: Optional[float],
                *, fold: int) -> Optional[Dict[str, Any]]:
        """Judge the active actuation against one more fold's swarm
        throughput. Returns the actuation record once a verdict lands
        (``record["verdict"]`` is ``"rollback"`` or ``"kept"``), else
        None. The pre-change level — NOT the pre-incident baseline — is
        the rollback reference: the actuation exists because throughput
        already regressed, so the rail only asks "did the change make it
        WORSE", never "did it fix everything"."""
        record = self.active
        if record is None or samples_per_sec is None:
            return None
        if fold - record["fold"] < self.cfg.settle_folds:
            return None
        value = float(samples_per_sec)
        record["observed"].append(round(value, 6))
        baseline = record.get("baseline_samples_per_sec")
        if (
            baseline
            and value < (1.0 - self.cfg.rollback_margin) * float(baseline)
        ):
            record["verdict"] = "rollback"
            record["verdict_fold"] = fold
            self.active = None
            self._cooldown_until = fold + self.cfg.cooldown_folds
            return record
        if len(record["observed"]) >= self.cfg.observe_folds:
            record["verdict"] = "kept"
            record["verdict_fold"] = fold
            self.active = None
            self._cooldown_until = fold + self.cfg.cooldown_folds
            return record
        return None


def rollback_effect(incident: Dict[str, Any],
                    record: Dict[str, Any]) -> Dict[str, Any]:
    """Append (and return) the ``rollback`` effect for a rolled-back
    actuation — the caller re-applies ``record["revert"]`` itself and then
    records the fact here, so the incident chain reads
    actuation → rollback in ``runlog_summary --incidents``."""
    baseline = record.get("baseline_samples_per_sec")
    observed = record["observed"][-1] if record.get("observed") else None
    deviation = None
    if baseline and observed is not None:
        deviation = round(float(observed) / float(baseline) - 1.0, 4)
    effect = {
        "metric": "rollback",
        "deviation": deviation,
        "fold": record.get("verdict_fold", record["fold"]),
        "applied": dict(record.get("revert") or {}),
        "verdict": (
            "post-change samples/sec regressed past the pre-change level"
        ),
    }
    incident.setdefault("effects", []).append(effect)
    return effect


def attach_recommendation(
    incident: Dict[str, Any],
    rows: List[Dict[str, Any]],
    seed: int = 0,
    grid: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Compute and attach the twin-backed recommendation for one
    retune-eligible incident. Idempotent: an incident that already carries
    a recommendation (or a reason) is returned unchanged."""
    if "recommendation" in incident or "recommendation_reason" in incident:
        return incident
    result = twin_recommendation(rows, seed=seed, grid=grid)
    if "no_recommendation" in result:
        incident["recommendation_reason"] = result["no_recommendation"]
        logger.warning(
            f"watchdog incident {incident['id']}: no retuning "
            f"recommendation — {result['no_recommendation']}"
        )
    else:
        incident["recommendation"] = result
        logger.info(
            f"watchdog incident {incident['id']}: twin recommends "
            f"{result['config']} (predicted "
            f"{result['predicted_samples_per_sec']} samples/sec, "
            f"±{result['fidelity_bound'] * 100:.0f}%)"
        )
    return incident
