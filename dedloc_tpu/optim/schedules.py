"""LR schedules driven by the GLOBAL collaboration step.

The reference hands LR control to CollaborativeOptimizer's internal scheduler
(NoOpScheduler shim at albert/run_trainer.py:189-207; get_linear_schedule_with
_warmup at :95-100; LinearWarmupCosineAnnealingLR at
sgd_collaborative.py:25-84). Here schedules are pure functions of the global
optimizer step, evaluated inside the jitted update.
"""
from __future__ import annotations

import jax.numpy as jnp
import optax


def linear_warmup_linear_decay(
    peak_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """transformers.get_linear_schedule_with_warmup equivalent."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = step / jnp.maximum(1.0, warmup_steps)
        decay = jnp.maximum(
            0.0, (total_steps - step) / jnp.maximum(1.0, total_steps - warmup_steps)
        )
        return peak_lr * jnp.where(step < warmup_steps, warm, decay)

    return schedule


def linear_warmup_cosine_annealing(
    peak_lr: float,
    warmup_steps: int,
    total_steps: int,
    warmup_start_lr: float = 0.0,
    eta_min: float = 0.0,
) -> optax.Schedule:
    """LinearWarmupCosineAnnealingLR equivalent (sgd_collaborative.py:25-84)."""

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warm = warmup_start_lr + (peak_lr - warmup_start_lr) * step / jnp.maximum(
            1.0, warmup_steps
        )
        progress = (step - warmup_steps) / jnp.maximum(1.0, total_steps - warmup_steps)
        progress = jnp.clip(progress, 0.0, 1.0)
        cos = eta_min + (peak_lr - eta_min) * 0.5 * (1 + jnp.cos(jnp.pi * progress))
        return jnp.where(step < warmup_steps, warm, cos)

    return schedule
