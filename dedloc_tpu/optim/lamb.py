"""LAMB optimizer (layer-wise adaptive moments) in optax style.

Capability parity with the reference recipe (albert/run_trainer.py:73-100):
torch_optimizer.Lamb(lr=..., betas=(0.9, 0.999), eps=1e-6, weight_decay=0.01,
clamp_value=10000, debias=True) with weight decay excluded for bias and
LayerNorm parameters. Implemented as composable optax gradient transforms so
the whole update runs inside the jitted train step (no host round-trip).

``scale_by_lamb`` and the full ``lamb`` chain share ONE implementation of
the Adam moments / debias / trust-ratio math (the helpers below) — the two
used to carry inline near-copies, and the flat-segment formulation
(``optim/flat.py``) adds a third consumer: any drift between them would be
a silent numerics bug, so the math lives in exactly one place. The helpers
are written with ``jax.tree.map`` so they work unchanged on parameter
PYTREES and on the one-leaf flat-buffer form.
"""
from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import chex
import jax
import jax.numpy as jnp
import optax


class ScaleByLambState(NamedTuple):
    count: chex.Array
    mu: optax.Updates
    nu: optax.Updates


def lamb_moments(
    updates, mu, nu, count, b1: float, b2: float, debias: bool
) -> Tuple[Any, Any, Any, Any, chex.Array]:
    """One Adam moment step: returns (mu, nu, mu_hat, nu_hat, count+1).

    ``mu_hat``/``nu_hat`` carry the (optional) bias correction; with
    ``debias=False`` they alias the raw moments. Structure-agnostic: the
    arguments may be parameter pytrees or single flat vectors.
    """
    mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, mu, updates)
    nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * g * g, nu, updates)
    count = count + 1
    if debias:
        c = count.astype(jnp.float32)
        mu_hat = jax.tree.map(lambda m: m / (1 - b1 ** c), mu)
        nu_hat = jax.tree.map(lambda v: v / (1 - b2 ** c), nu)
    else:
        mu_hat, nu_hat = mu, nu
    return mu, nu, mu_hat, nu_hat, count


def adam_direction(mu_hat, nu_hat, eps: float):
    """m / (sqrt(v) + eps), leaf-wise."""
    return jax.tree.map(lambda m, v: m / (jnp.sqrt(v) + eps), mu_hat, nu_hat)


def trust_ratio_scale(
    w_norm: jnp.ndarray, u_norm: jnp.ndarray, clamp_value: float
) -> jnp.ndarray:
    """The LAMB layer-wise trust ratio from precomputed norms:
    ``min(||w||, clamp_value) / ||u||`` where both norms are positive,
    else 1.0 (torch_optimizer.Lamb ``clamp_value`` semantics)."""
    w_norm = jnp.minimum(w_norm, clamp_value)
    return jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)


def apply_trust_ratio(w, u, clamp_value: float):
    """Per-leaf trust-ratio scaling of update ``u`` against params ``w``."""
    w_norm = jnp.linalg.norm(w.astype(jnp.float32))
    u_norm = jnp.linalg.norm(u.astype(jnp.float32))
    return u * trust_ratio_scale(w_norm, u_norm, clamp_value)


def scale_by_lamb(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    clamp_value: float = 10000.0,
    debias: bool = True,
) -> optax.GradientTransformation:
    """Adam moments + layer-wise trust ratio with weight-norm clamp.

    The trust ratio is ``min(||w||, clamp_value) / ||adam_update||``, matching
    torch_optimizer.Lamb's ``clamp_value`` semantics.
    """

    def init_fn(params):
        mu = jax.tree.map(jnp.zeros_like, params)
        nu = jax.tree.map(jnp.zeros_like, params)
        return ScaleByLambState(count=jnp.zeros([], jnp.int32), mu=mu, nu=nu)

    def update_fn(updates, state, params):
        assert params is not None, "lamb requires params"
        mu, nu, mu_hat, nu_hat, count = lamb_moments(
            updates, state.mu, state.nu, state.count, b1, b2, debias
        )
        adam_step = adam_direction(mu_hat, nu_hat, eps)
        updates = jax.tree.map(
            lambda w, u: apply_trust_ratio(w, u, clamp_value),
            params, adam_step,
        )
        return updates, ScaleByLambState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


def albert_weight_decay_mask(params) -> Any:
    """True where weight decay applies: everything except biases and
    LayerNorm/embedding-LN scale/bias (reference: run_trainer.py:78-87
    no_decay = ["bias", "LayerNorm.weight"])."""

    def decide(path, _):
        names = [p.key for p in path if hasattr(p, "key")]
        joined = "/".join(names).lower()
        if names and names[-1] == "bias":
            return False
        if "layernorm" in joined or "layer_norm" in joined:
            return False
        return True

    return jax.tree_util.tree_map_with_path(decide, params)


def lamb(
    learning_rate: optax.ScalarOrSchedule,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-6,
    weight_decay: float = 0.01,
    clamp_value: float = 10000.0,
    debias: bool = True,
    weight_decay_mask: Optional[Callable] = albert_weight_decay_mask,
    max_grad_norm: Optional[float] = None,
) -> optax.GradientTransformation:
    """Full LAMB chain: [clip] -> moments+decay -> trust ratio -> lr.

    Weight decay is added to the adam update BEFORE the trust ratio (the
    torch_optimizer.Lamb formulation the reference trains with).
    """
    # Decay must enter before the trust-ratio scaling, so we fold it into the
    # update inside a custom wrapper around the shared scale_by_lamb math.
    inner = scale_by_lamb(b1, b2, eps, clamp_value, debias)

    def init_fn(params):
        return inner.init(params)

    def update_fn(updates, state, params):
        # the same moments -> +wd*param -> trust ordering as scale_by_lamb,
        # through the SAME helpers — only the weight-decay insertion differs
        mu, nu, mu_hat, nu_hat, count = lamb_moments(
            updates, state.mu, state.nu, state.count, b1, b2, debias
        )
        adam_step = adam_direction(mu_hat, nu_hat, eps)

        if weight_decay > 0.0:
            mask = (
                weight_decay_mask(params)
                if callable(weight_decay_mask)
                else jax.tree.map(lambda _: True, params)
            )
            adam_step = jax.tree.map(
                lambda u, w, m: u + weight_decay * w if m else u,
                adam_step,
                params,
                mask,
                is_leaf=lambda x: x is None,
            )

        updates = jax.tree.map(
            lambda w, u: apply_trust_ratio(w, u, clamp_value),
            params, adam_step,
        )
        new_state = ScaleByLambState(count=count, mu=mu, nu=nu)
        return updates, new_state

    chain = [optax.GradientTransformation(init_fn, update_fn)]
    if max_grad_norm is not None:
        chain.insert(0, optax.clip_by_global_norm(max_grad_norm))
    chain.append(
        optax.scale_by_learning_rate(learning_rate)  # negates for descent
    )
    return optax.chain(*chain)
