"""LARS / LARC optimizer for SwAV (pure JAX, replaces apex LARC).

Capability parity with the reference's SGD -> apex LARC(BLYARC) wrap
(swav/ClassyVision/classy_vision/optim/sgd_collaborative.py:139-144):
per-layer trust-ratio-clipped SGD with momentum and weight decay. LARC in
"clip" mode caps the effective LR at ``trust_coefficient * ||w|| / ||g||``.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax


class LarsState(NamedTuple):
    momentum: optax.Updates


def lars(
    learning_rate: optax.ScalarOrSchedule,
    momentum: float = 0.9,
    weight_decay: float = 1e-6,
    trust_coefficient: float = 0.001,
    eps: float = 1e-8,
    clip: bool = True,
    exclude_mask_fn: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """LARC-style SGD: local-lr = trust * ||w|| / (||g|| + wd*||w||), clipped
    at the global LR when ``clip`` (apex LARC clip=True semantics)."""

    def init_fn(params):
        return (LarsState(momentum=jax.tree.map(jnp.zeros_like, params)),
                optax.ScaleByScheduleState(count=jnp.zeros([], jnp.int32)))

    def update_fn(updates, state, params):
        lars_state, sched_state = state
        count = sched_state.count
        lr = learning_rate(count) if callable(learning_rate) else learning_rate
        excluded = (
            exclude_mask_fn(params)
            if exclude_mask_fn is not None
            else jax.tree.map(lambda _: False, params)
        )

        def adapt(g, w, skip):
            g = g + weight_decay * w
            if skip:
                return -lr * g
            w_norm = jnp.linalg.norm(w.astype(jnp.float32))
            g_norm = jnp.linalg.norm(g.astype(jnp.float32))
            local_lr = trust_coefficient * w_norm / (g_norm + eps)
            if clip:
                local_lr = jnp.minimum(local_lr / jnp.maximum(lr, 1e-12), 1.0) * lr
            else:
                local_lr = local_lr * lr
            local_lr = jnp.where((w_norm > 0) & (g_norm > 0), local_lr, lr)
            return -local_lr * g

        scaled = jax.tree.map(adapt, updates, params, excluded)
        new_mom = jax.tree.map(
            lambda m, u: momentum * m + u, lars_state.momentum, scaled
        )
        return new_mom, (LarsState(momentum=new_mom),
                         optax.ScaleByScheduleState(count=count + 1))

    return optax.GradientTransformation(init_fn, update_fn)
