from dedloc_tpu.optim.lamb import lamb, albert_weight_decay_mask
from dedloc_tpu.optim.lars import lars
from dedloc_tpu.optim.schedules import (
    linear_warmup_linear_decay,
    linear_warmup_cosine_annealing,
)
