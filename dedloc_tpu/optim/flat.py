"""Flat-segment LAMB / LARS: the optimizer math over ONE flat buffer.

The averaging path already lives on a flat fp32 vector (``TreeLayout``,
``averaging/partition.py``): every peer flattens its gradient tree into one
buffer, ships it, and unflattens the averaged result. The optimizer apply,
however, historically re-entered tree-land — per-leaf moment updates,
per-leaf norm reductions, a host round-trip per leaf when the averaged
result came back. This module closes the loop: the full LAMB/LARS update —
moments, debias, weight decay, per-layer trust ratios — computed directly
on the flat buffer, with per-layer reductions expressed as SEGMENT
reductions over the layout's contiguous spans.

Numerics: the math is the SAME code as the tree chain (``lamb_moments`` /
``adam_direction`` / ``trust_ratio_scale`` from ``optim/lamb.py`` — a flat
vector is a one-leaf pytree), so the only differences are reduction order
(a span reduce sums the same elements as the per-leaf norm, but XLA may
re-associate differently) and the clip/decay mask expansion. Equivalence vs
the per-leaf optax chain is locked by ``tests/test_optim.py`` to 25-step
agreement within a documented float32 bound.

These adapters are consumed by ``parallel.train_step.make_flat_apply_step``,
which keeps the OPTAX TREE STATE as the persistent ``opt_state`` (so
checkpoints, peer state sync and ZeRO layouts are untouched) and converts
tree<->flat inside the one fused jit.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dedloc_tpu.optim.lamb import (
    adam_direction,
    lamb_moments,
    trust_ratio_scale,
)


def spec_spans(
    spec: Sequence[Tuple[str, Tuple[int, ...], np.dtype]]
) -> List[Tuple[int, int]]:
    """Contiguous (offset, size) spans of each spec entry in the flat
    buffer — the segment boundaries every per-layer reduction uses."""
    spans = []
    offset = 0
    for _name, shape, _dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        spans.append((offset, size))
        offset += size
    return spans


def segment_sumsq(flat: jnp.ndarray, spans) -> jnp.ndarray:
    """Per-segment sum of squares over the flat buffer: one slice-reduce
    per contiguous span (XLA fuses the slices; no gather/scatter and no
    O(N) segment-id constant). Empty spans contribute 0."""
    parts = [
        jnp.vdot(flat[o:o + s], flat[o:o + s]).real if s else jnp.float32(0.0)
        for o, s in spans
    ]
    return jnp.stack([jnp.asarray(p, jnp.float32) for p in parts])


def expand_segments(
    per_segment: jnp.ndarray, spans, total: int
) -> jnp.ndarray:
    """Broadcast a [num_segments] vector back to the flat [total] buffer
    (inverse of a segment reduction)."""
    sizes = jnp.asarray([s for _o, s in spans], jnp.int32)
    return jnp.repeat(per_segment, sizes, total_repeat_length=total)


class FlatLamb:
    """The full ``optim.lamb.lamb`` chain ([clip] -> moments+decay -> trust
    -> lr) over one flat fp32 buffer.

    ``decay_flags`` / ``spans`` follow the TreeLayout spec order (sorted
    names). ``update`` is pure and jit-friendly; moments stay flat vectors
    between calls only inside the enclosing jit — the persistent state
    remains the tree chain's (see ``make_flat_apply_step``).
    """

    def __init__(
        self,
        spec,
        decay_flags: Sequence[bool],
        learning_rate: optax.ScalarOrSchedule,
        b1: float = 0.9,
        b2: float = 0.999,
        eps: float = 1e-6,
        weight_decay: float = 0.01,
        clamp_value: float = 10000.0,
        debias: bool = True,
        max_grad_norm: Optional[float] = None,
    ) -> None:
        self.spans = spec_spans(spec)
        self.total = sum(s for _o, s in self.spans)
        self.decay_flags = np.asarray(list(decay_flags), np.float32)
        assert len(self.decay_flags) == len(self.spans)
        self.learning_rate = learning_rate
        self.b1, self.b2, self.eps = b1, b2, eps
        self.weight_decay = float(weight_decay)
        self.clamp_value = float(clamp_value)
        self.debias = bool(debias)
        self.max_grad_norm = max_grad_norm

    def _lr(self, sched_count):
        if callable(self.learning_rate):
            return self.learning_rate(sched_count)
        return jnp.asarray(self.learning_rate, jnp.float32)

    def update(
        self,
        flat_grads: jnp.ndarray,
        flat_params: jnp.ndarray,
        flat_mu: jnp.ndarray,
        flat_nu: jnp.ndarray,
        count: jnp.ndarray,
        sched_count: jnp.ndarray,
    ):
        """One LAMB step on flat buffers. Returns
        (flat_updates, new_flat_mu, new_flat_nu, new_count) where
        ``flat_updates`` is the DELTA to add to the params (lr folded in,
        descent-negated — optax ``apply_updates`` convention)."""
        g = flat_grads
        if self.max_grad_norm is not None:
            # optax.clip_by_global_norm semantics on the flat buffer: the
            # global norm IS the one vdot
            g_norm = jnp.sqrt(jnp.vdot(g, g).real)
            g = jnp.where(
                g_norm < self.max_grad_norm, g,
                (g / g_norm) * self.max_grad_norm,
            )
        mu, nu, mu_hat, nu_hat, count = lamb_moments(
            g, flat_mu, flat_nu, count, self.b1, self.b2, self.debias
        )
        adam_step = adam_direction(mu_hat, nu_hat, self.eps)
        if self.weight_decay > 0.0:
            decay = expand_segments(
                jnp.asarray(self.decay_flags), self.spans, self.total
            )
            adam_step = adam_step + self.weight_decay * decay * flat_params
        # per-layer trust ratios as segment reductions over the flat buffer
        w_norm = jnp.sqrt(segment_sumsq(flat_params, self.spans))
        u_norm = jnp.sqrt(segment_sumsq(adam_step, self.spans))
        ratio = trust_ratio_scale(w_norm, u_norm, self.clamp_value)
        trusted = adam_step * expand_segments(ratio, self.spans, self.total)
        lr = self._lr(sched_count)
        return -lr * trusted, mu, nu, count


class FlatLars:
    """The full ``optim.lars.lars`` LARC-style update over one flat fp32
    buffer: per-layer local LR from segment norms, momentum folded in.
    ``excluded_flags`` marks spans the trust adaptation skips (plain SGD)."""

    def __init__(
        self,
        spec,
        excluded_flags: Sequence[bool],
        learning_rate: optax.ScalarOrSchedule,
        momentum: float = 0.9,
        weight_decay: float = 1e-6,
        trust_coefficient: float = 0.001,
        eps: float = 1e-8,
        clip: bool = True,
    ) -> None:
        self.spans = spec_spans(spec)
        self.total = sum(s for _o, s in self.spans)
        self.excluded_flags = np.asarray(list(excluded_flags), np.float32)
        assert len(self.excluded_flags) == len(self.spans)
        self.learning_rate = learning_rate
        self.momentum = float(momentum)
        self.weight_decay = float(weight_decay)
        self.trust_coefficient = float(trust_coefficient)
        self.eps = float(eps)
        self.clip = bool(clip)

    def _lr(self, sched_count):
        if callable(self.learning_rate):
            return self.learning_rate(sched_count)
        return jnp.asarray(self.learning_rate, jnp.float32)

    def update(
        self,
        flat_grads: jnp.ndarray,
        flat_params: jnp.ndarray,
        flat_momentum: jnp.ndarray,
        sched_count: jnp.ndarray,
    ):
        """One LARS step on flat buffers. Returns
        (flat_updates, new_flat_momentum) — updates are the delta to add
        to the params (the new momentum, per the reference LARC wrap)."""
        lr = self._lr(sched_count)
        g = flat_grads + self.weight_decay * flat_params
        w_norm = jnp.sqrt(segment_sumsq(flat_params, self.spans))
        g_norm = jnp.sqrt(segment_sumsq(g, self.spans))
        local_lr = self.trust_coefficient * w_norm / (g_norm + self.eps)
        if self.clip:
            local_lr = (
                jnp.minimum(local_lr / jnp.maximum(lr, 1e-12), 1.0) * lr
            )
        else:
            local_lr = local_lr * lr
        local_lr = jnp.where((w_norm > 0) & (g_norm > 0), local_lr, lr)
        # excluded spans take the plain -lr * g step (apex LARC skip list)
        excl = expand_segments(
            jnp.asarray(self.excluded_flags), self.spans, self.total
        )
        per_elem_lr = expand_segments(local_lr, self.spans, self.total)
        scaled = -(excl * lr + (1.0 - excl) * per_elem_lr) * g
        new_mom = self.momentum * flat_momentum + scaled
        return new_mom, new_mom


def tree_flags(mask_tree, template, spec_names: Sequence[str]) -> List[bool]:
    """Per-spec-entry boolean flags from a per-leaf mask pytree (e.g.
    ``albert_weight_decay_mask``), reordered into the sorted-name spec
    order the flat buffer uses."""
    flat = jax.tree_util.tree_flatten_with_path(template)[0]
    mask_leaves = jax.tree.leaves(
        mask_tree, is_leaf=lambda x: isinstance(x, bool)
    )
    by_name = {}
    for i, ((path, _leaf), flag) in enumerate(zip(flat, mask_leaves)):
        name = jax.tree_util.keystr(path) or f"leaf{i}"
        by_name[name] = bool(flag)
    return [by_name[name] for name in spec_names]
