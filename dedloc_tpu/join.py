"""One-command volunteer onboarding: ``python -m dedloc_tpu.join``.

The executable equivalent of the reference's contributor notebook
(sahajbert/contributor_notebook.ipynb, 4 cells: install → authorize → join
DHT → train). Everything the notebook does interactively happens here from
one command:

    python -m dedloc_tpu.join \\
        --initial_peers COORD_HOST:31337 \\
        --experiment_prefix THE_RUN_NAME \\
        --username alice --credential s3cret

1. **authorize** (gated runs): fetches a signed access token from the
   coordinator's AuthService, failing fast on bad credentials (cell 2).
2. **join**: connects to the DHT via any live peer, downloads the newest
   model+optimizer state from the collaboration (cell 3's
   ``load_state_from_peers`` — no checkpoint files needed).
3. **train**: accumulates gradients and participates in group averaging
   until interrupted; leaving at any time only costs the current group one
   round (cell 3's butterfly-averaging prose).

Open runs omit ``--username``. Firewalled volunteers add ``--client_mode``
(and optionally ``--relay HOST:PORT``); NAT traversal upgrades their
connections to direct paths automatically (docs/transport.md). Any advanced
dotted flag of the full trainer surface can be appended verbatim, e.g.
``--training.per_device_batch_size 8``.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def build_trainer_argv(argv: Optional[List[str]] = None) -> List[str]:
    """Map the friendly flag surface onto the trainer's dotted config tree;
    unknown (dotted) flags pass through untouched."""
    parser = argparse.ArgumentParser(
        prog="python -m dedloc_tpu.join",
        description="Join a collaborative training run as a volunteer peer.",
    )
    parser.add_argument("--initial_peers", required=True,
                        help="host:port of any live peer (comma-separated)")
    parser.add_argument("--experiment_prefix", required=True,
                        help="the run's name (ask the organizers)")
    parser.add_argument("--username", default="",
                        help="allowlisted username (gated runs only)")
    parser.add_argument("--credential", default="",
                        help="access credential for --username")
    parser.add_argument("--auth_endpoint", default="",
                        help="host:port of the AuthService "
                             "(default: the first initial peer)")
    parser.add_argument("--client_mode", action="store_true",
                        help="outbound-only (behind a firewall/NAT)")
    parser.add_argument("--relay", default="",
                        help="host:port of a public peer's relay")
    parser.add_argument("--batch_size", type=int, default=4,
                        help="per-device micro-batch size")
    known, passthrough = parser.parse_known_args(argv)

    # the trainer's list flags are space-separated (nargs="*"); the friendly
    # surface documents comma-separated, so split here
    peers = [p for p in known.initial_peers.split(",") if p]
    trainer_argv = [
        "--dht.initial_peers", *peers,
        "--dht.experiment_prefix", known.experiment_prefix,
        "--training.per_device_batch_size", str(known.batch_size),
    ]
    if known.username:
        trainer_argv += ["--auth.username", known.username,
                         "--auth.credential", known.credential]
    if known.auth_endpoint:
        trainer_argv += ["--auth.endpoint", known.auth_endpoint]
    if known.client_mode:
        trainer_argv += ["--dht.client_mode", "true"]
    if known.relay:
        trainer_argv += ["--dht.relay", known.relay]
    return trainer_argv + passthrough


def main(argv: Optional[List[str]] = None) -> None:
    from dedloc_tpu.core.config import CollaborationArguments, parse_config
    from dedloc_tpu.roles.trainer import run_trainer

    args = parse_config(CollaborationArguments, build_trainer_argv(argv))
    state = run_trainer(args)
    print(f"left the collaboration at global step {int(state.step)}")


if __name__ == "__main__":
    main()
