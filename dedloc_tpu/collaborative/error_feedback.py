"""Error feedback for lossy wire compression (DGC / 1-bit-SGD lineage).

A lossy wire format (float16, uint8 — core/serialization.py) drops a
quantization residual from every contributed gradient. Left alone that
residual is a per-round bias: the trunk consistently loses whatever the
codec rounds away, and with coarse formats (uint8) the loss is large enough
to bend convergence. The classic fix (Deep Gradient Compression, 1-bit SGD,
PowerSGD's EF trick) is to FEED THE RESIDUAL BACK: add the error the codec
made last round into this round's contribution before encoding, so over
time every gradient component is eventually transmitted — the cumulative
transmitted signal tracks the cumulative true gradient to within one
residual (bounded, no drift).

    contrib_t  = grad_t + residual_{t-1}
    residual_t = contrib_t - wire(contrib_t)

The residual is tracked per tensor on the host (numpy, never on device —
it rides the same jit↔asyncio seam as the averaging itself). ``wire`` here
is the codec round-trip applied per tensor; the actual all-reduce encodes
per CHUNK of the flat vector, whose uint8 quantization grid can differ
slightly at chunk boundaries — the residual is a (tight) approximation of
the true wire error, which error feedback tolerates by construction: any
mis-estimate simply lands in a later residual.

Commit discipline: ``prepare`` returns the contribution plus a ``commit``
callback, and the caller invokes commit ONLY when the round actually
averaged (a failed round transmitted nothing — updating the residual for it
would discard real gradient signal).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dedloc_tpu.core.serialization import CompressionType, wire_roundtrip


class ErrorFeedback:
    """Per-tensor residual buffer for one peer's averaging contributions."""

    def __init__(self, compression: str | CompressionType):
        self.compression = (
            CompressionType(compression)
            if isinstance(compression, str)
            else compression
        )
        self._residual: Dict[str, np.ndarray] = {}

    @property
    def enabled(self) -> bool:
        return self.compression is not CompressionType.NONE

    def prepare(
        self, named: Dict[str, np.ndarray]
    ) -> Tuple[Dict[str, np.ndarray], Callable[[], None]]:
        """Return (contribution with residual folded in, commit callback).

        The commit callback adopts this round's residual; call it only once
        the round's result actually landed. Until then the stored residual
        stays that of the last SUCCESSFUL round, so retries re-derive the
        same contribution instead of compounding."""
        if not self.enabled:
            return named, lambda: None
        contrib: Dict[str, np.ndarray] = {}
        new_residual: Dict[str, np.ndarray] = {}
        for name, grad in named.items():
            grad = np.asarray(grad, dtype=np.float32)
            res = self._residual.get(name)
            carried = grad if res is None else grad + res
            contrib[name] = carried
            new_residual[name] = carried - wire_roundtrip(
                carried, self.compression
            )

        def commit() -> None:
            self._residual = new_residual

        return contrib, commit

    def reset(self) -> None:
        """Drop the residual — after a state resync the carried error belongs
        to gradients computed on params this peer no longer holds."""
        self._residual = {}

    def residual_norm(self) -> float:
        """Global L2 norm of the stored residual (telemetry / drift tests)."""
        total = 0.0
        for res in self._residual.values():
            total += float(np.vdot(res, res).real)
        return float(np.sqrt(total))
