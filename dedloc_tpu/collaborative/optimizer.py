"""CollaborativeOptimizer: the TPU-native DeDLOC training driver.

Semantics parity with hivemind.CollaborativeOptimizer as consumed by all
three reference trainers (SURVEY.md §2.6, §3.1): accumulate gradients
locally until the COLLABORATION-wide sample count reaches
``target_batch_size``, then form a group, average gradients (weighted by
each peer's accumulated samples) and apply one optimizer step keyed by the
GLOBAL step counter. Exposes ``local_step``, ``collaboration_state``,
``is_synchronized``, ``performance_ema``, ``local_samples_accumulated``,
``load_state_from_peers`` and ``step_aux`` — the exact attribute surface the
reference trainers consume.

TPU-native split (SURVEY.md §7 hard-parts b,c):
- the hot path stays jitted: callers run ``make_accumulate_step`` per
  micro-batch with a device-resident, donated grad accumulator;
- ``step`` crosses the jit↔asyncio seam exactly once per GLOBAL step
  (device_get of the mean grads), not per micro-batch;
- the slice (not the chip) is the collaboration peer: in-slice averaging is
  the psum XLA already inserted, this class only averages across slices.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np
import optax

from dedloc_tpu.averaging.allreduce import DEFAULT_CHUNK_SIZE
from dedloc_tpu.averaging.averager import DecentralizedAverager
from dedloc_tpu.averaging.device_flat import DeviceFlatPipeline
from dedloc_tpu.averaging.partition import FlatTree
from dedloc_tpu.collaborative.error_feedback import ErrorFeedback
from dedloc_tpu.collaborative.progress import (
    CollaborationState,
    LocalProgress,
    ProgressTracker,
)
from dedloc_tpu.core.timeutils import PerformanceEMA, get_dht_time
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry import steps
from dedloc_tpu.telemetry.registry import monotonic_clock
from dedloc_tpu.parallel.train_step import (
    TrainState,
    make_flat_apply_step,
    make_guarded_apply_step,
    zeros_like_grads,
)
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _tree_to_named(tree) -> Dict[str, np.ndarray]:
    """Flatten a pytree into {path: np.array} with deterministic names."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = {}
    for i, (path, leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path) or f"leaf{i}"
        out[name] = np.asarray(leaf)
    return out


def _named_to_tree(named: Dict[str, np.ndarray], like):
    """Inverse of _tree_to_named given a structural template."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for i, (path, leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path) or f"leaf{i}"
        arr = named[name]
        leaves.append(np.asarray(arr, dtype=leaf.dtype).reshape(leaf.shape))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(like), leaves
    )


@jax.jit
def _fused_mean_clip(grad_acc, n, cap):
    """The accumulator mean plus the contribution clip as ONE fused jitted
    program: ``grad_acc / n`` per leaf, one global-norm reduce, one scale.
    ``cap <= 0`` disables the clip (the scale multiplies by exactly 1.0, a
    bitwise no-op). Replaces the Python-level sum of per-leaf ``vdot``s
    that used to emit O(leaves) tiny kernels per boundary."""
    mean = jax.tree.map(lambda g: g / n, grad_acc)
    gnorm = jax.numpy.sqrt(
        sum(
            jax.numpy.vdot(g, g).real
            for g in jax.tree.leaves(mean)
        )
    )
    scale = jax.numpy.where(
        cap > 0, jax.numpy.minimum(1.0, cap / (gnorm + 1e-12)), 1.0
    )
    return jax.tree.map(lambda g: g * scale, mean)


class CollaborativeOptimizer:
    def __init__(
        self,
        tx: optax.GradientTransformation,
        dht: DHT,
        prefix: str,
        target_batch_size: int = 4096,
        batch_size_per_step: Optional[int] = None,
        batch_size_lead: int = 0,
        bandwidth: float = 1000.0,
        compression: str = "float16",
        target_group_size: int = 256,
        averaging_expiration: float = 5.0,
        averaging_timeout: float = 30.0,
        metadata_expiration: float = 30.0,
        statistics_expiration: float = 600.0,
        min_refresh_period: float = 0.5,
        max_refresh_period: float = 30.0,
        default_refresh_period: float = 3.0,
        expected_drift_peers: float = 3.0,
        expected_drift_rate: float = 0.2,
        performance_ema_alpha: float = 0.1,
        client_mode: bool = False,
        relay: Optional[str] = None,  # circuit relay for client-mode peers
        auxiliary: bool = False,
        allow_state_sharing: bool = True,
        mesh=None,
        opt_state_sharding=None,  # ZeRO-1 moment layout (parallel.zero)
        param_sharding=None,  # tensor-parallel layout (parallel.sharding)
        verbose: bool = False,
        listen_host: str = "0.0.0.0",
        listen_port: int = 0,  # fixed averager port (0 = ephemeral); a
        # listening averager doubles as a circuit relay, so public peers in
        # relayed deployments want this pinned (--averager.listen_port)
        advertised_host: Optional[str] = None,
        post_apply: Optional[Callable[[TrainState], TrainState]] = None,
        authorizer=None,  # token authorizer for gated public runs
        authority_public_key: Optional[bytes] = None,
        contrib_clip_per_sample: float = 0.0,  # cap the contributed
        # per-MICRO-batch mean grad at clip*(samples/micro-batch) before
        # averaging — tiny-batch peers inject high-per-sample-energy noise
        # otherwise (core/config.py CollaborativeOptimizerArguments)
        ramp_rounds: int = 0,  # contribution ramp (0 = off): scale this
        # peer's averaging weight from near-zero to its full sample count
        # over its first ramp_rounds completed global steps — a fresh
        # joiner receives the group's direction while barely perturbing it
        # during basin formation (the enforced form of docs/fleet.md's
        # "onboard onto a formed trunk" guidance)
        health_gate_loss_ratio: float = 0.0,  # trunk-health gate (0 = off):
        # while this peer's advertised loss exceeds ratio x the median
        # advertised loss of the OTHER trainers, it defers mixing entirely
        # (contributes weight 0, still receives the group average)
        state_sync_retries: int = 2,  # bounded state-download retry with
        state_sync_backoff: float = 0.5,  # exponential backoff (averager)
        checkpoint_shard_size: int = 1 << 20,  # swarm checkpointing
        # (--checkpoint.*, dedloc_tpu/checkpointing): fp32 elements per
        # content-addressed shard of the shared state; <= 0 disables the
        # sharded serve/catalog/restore path (full blob only). Defaults ON
        # here (deployment surface) while the bare averager defaults OFF.
        checkpoint_fetch_parallelism: int = 4,
        checkpoint_max_providers: int = 0,
        checkpoint_dir: Optional[str] = None,  # local shard cache for
        # resumable restores (None = in-memory only)
        signed_subkey: Optional[bytes] = None,  # the peer's signed metrics
        # subkey: catalog announcements ride it so they are signature-bound
        chunk_size: int = DEFAULT_CHUNK_SIZE,  # elements per wire chunk in
        # the pipelined all-reduce; <= 0 restores monolithic spans (the
        # pre-pipeline wire format) — same contract as --averager.chunk_size
        topology_plan=None,  # hierarchical two-level averaging plan
        # (averaging/topology.py; --averager.topology_plan): a TopologyPlan
        # or a path to its JSON. None / mode="flat" keeps the flat
        # butterfly; failures inside a hierarchical round fall back to a
        # flat retry of the same round automatically.
        plan_follow: bool = False,  # live re-planning (planwire.py):
        # poll the coordinator's epoch-versioned plan record and adopt the
        # newest valid plan between rounds; the roles enable this unless a
        # manual topology_plan is pinned (the opt-out, docs/fleet.md)
        plan_refresh_period: float = 30.0,
        error_feedback: bool = True,  # residual error feedback for lossy
        # wire compression: the previous round's quantization error is added
        # back into the next round's contribution, so float16/uint8 wire
        # formats don't bias the trunk (collaborative/error_feedback.py).
        # No-op under compression="none".
        overlap_averaging: bool = False,  # opt-in background averaging: at
        # a round boundary the averaging round is launched on the executor
        # and the trainer KEEPS ACCUMULATING the next microbatches; the
        # averaged update is applied when the round lands — one boundary
        # late (bounded staleness). Auto-disabled during the contribution
        # ramp, while health-gated, and around state sync; a failed
        # overlapped round restores its gradients into the accumulator and
        # falls back to the synchronous path (docs/fleet.md).
        telemetry_registry=None,  # per-peer telemetry scope, forwarded to
        # the averager/matchmaking/RPC stack (telemetry/registry.py); None
        # falls back to the process-global registry at each site
        device_flat: bool = True,  # device-resident flat gradient pipeline
        # (averaging/device_flat.py): the boundary's mean/clip/error-
        # feedback/quantize all run in one fused jit on the accelerator and
        # the compressed representation streams to the host in async chunks
        # — the grad_flatten phase transfers 2-4x fewer PCIe bytes under a
        # lossy wire format and the host codec becomes decode-only. Falls
        # back to the legacy per-leaf host path automatically when the
        # gradient tree is refused (non-float leaves).
        flat_opt_factory: Optional[Callable] = None,  # (spec, params) ->
        # optim.flat.FlatLamb/FlatLars: enables the fused FLAT apply — the
        # averaged result device_puts as ONE buffer and the whole optimizer
        # update runs as segment reductions over it (make_flat_apply_step).
        # None (or any sharded layout) keeps the per-leaf guarded apply.
        ledger_claims: bool = True,  # contribution ledger
        # (telemetry/ledger.py): periodically publish this peer's signed
        # cumulative ContributionClaim DHT record off the progress-report
        # cadence; group-mates' RoundReceipts make it checkable
        claim_period: float = 30.0,  # dht-time seconds between claims
        ledger_receipts: bool = True,  # countersign averaging rounds into
        # RoundReceipt records (forwarded to the averager, which owns the
        # group envelope the receipt is built from)
    ):
        assert not (client_mode and auxiliary), "an auxiliary peer must listen"
        self.tx = tx
        self.dht = dht
        self.prefix = prefix
        self.target_batch_size = target_batch_size
        self.batch_size_per_step = batch_size_per_step
        self.client_mode = client_mode
        self.auxiliary = auxiliary
        self.verbose = verbose
        self.statistics_expiration = statistics_expiration
        self.contrib_clip_per_sample = float(contrib_clip_per_sample)
        self.ramp_rounds = int(ramp_rounds)
        self.health_gate_loss_ratio = float(health_gate_loss_ratio)
        # completed global steps since THIS optimizer joined — drives the
        # contribution ramp. Deliberately reset on restart: a rejoining
        # peer's params may have drifted while it was away, so it re-ramps.
        self._rounds_since_join = 0
        self._last_loss: Optional[float] = None
        self.telemetry = telemetry_registry
        self.overlap_averaging = bool(overlap_averaging)
        # in-flight overlapped round: {future, named, commit, collab,
        # samples, n_micro, partners_certain} — at most ONE at a time
        self._overlap_inflight: Optional[Dict[str, Any]] = None
        # after a failed overlapped round the next boundary runs the
        # synchronous path (and its retry/resync ladder); a successful
        # global step re-arms overlap
        self._overlap_cooldown = False
        # samples committed to the in-flight round: still advertised in
        # progress reports until the round lands — zeroing the advertised
        # count at an unchanged step would deflate the collaboration-wide
        # sum and flip partners' ready_for_step back off (the sync path
        # keeps its full count published throughout averaging and resets
        # only together with the step advance)
        self._overlap_committed_samples = 0
        # overlap ledger (docs/observability.md "overlap ledger"): per
        # boundary, how much of the averaging round's launch→finish wall was
        # HIDDEN behind concurrent accumulation vs EXPOSED as stall. Clocked
        # on the FakeClock-aware monotonic clock; only maintained when
        # overlap_averaging is configured (it measures that feature).
        self._overlap_launched_at = 0.0
        self._overlap_resumed_at: Optional[float] = None
        self._overlap_done_at: Optional[float] = None
        self._overlap_hidden_s = 0.0
        self.error_feedback = ErrorFeedback(
            compression if error_feedback else "none"
        )

        self.averager = DecentralizedAverager(
            dht,
            prefix,
            bandwidth=bandwidth,
            client_mode=client_mode,
            auxiliary=auxiliary,
            allow_state_sharing=allow_state_sharing and not auxiliary,
            compression=compression,
            chunk_size=chunk_size,
            averaging_expiration=averaging_expiration,
            averaging_timeout=averaging_timeout,
            target_group_size=target_group_size,
            listen_host=listen_host,
            listen_port=listen_port,
            advertised_host=advertised_host,
            authorizer=authorizer,
            authority_public_key=authority_public_key,
            relay=relay,
            state_sync_retries=state_sync_retries,
            state_sync_backoff=state_sync_backoff,
            checkpoint_shard_size=checkpoint_shard_size,
            checkpoint_fetch_parallelism=checkpoint_fetch_parallelism,
            checkpoint_max_providers=checkpoint_max_providers,
            checkpoint_dir=checkpoint_dir,
            signed_subkey=signed_subkey,
            telemetry_registry=telemetry_registry,
            topology_plan=topology_plan,
            plan_follow=plan_follow,
            plan_refresh_period=plan_refresh_period,
            ledger_receipts=ledger_receipts,
        )
        self.tracker = ProgressTracker(
            dht,
            prefix,
            peer_subkey=self.averager.peer_id,
            target_batch_size=target_batch_size,
            min_refresh_period=min_refresh_period,
            max_refresh_period=max_refresh_period,
            default_refresh_period=default_refresh_period,
            metadata_expiration=metadata_expiration,
            expected_drift_peers=expected_drift_peers,
            expected_drift_rate=expected_drift_rate,
            batch_size_lead=batch_size_lead,
        )
        self.performance_ema = PerformanceEMA(alpha=performance_ema_alpha)
        self._ema_started = False
        self._created_at = get_dht_time()
        self.local_step = 0
        self.local_samples_accumulated = 0
        self.mesh = mesh
        self.opt_state_sharding = opt_state_sharding
        self.param_sharding = param_sharding
        # post-update transform on the new state (e.g. SwAV prototype
        # re-normalization — NormalizePrototypesHook.on_update capability,
        # swav_hooks.py:55-92); runs once per GLOBAL step inside the SAME
        # jit as the apply and its NaN guard
        self.post_apply = post_apply
        # guarded apply: optimizer update + post_apply + fused all-finite
        # reduce + jnp.where rollback in ONE jitted program — no pre-apply
        # HBM copy of (step, params, opt_state), no host-synced finite
        # check; the ok flag is read one boundary later (_check_apply_ok)
        self._apply_fn = make_guarded_apply_step(
            tx, mesh=mesh, opt_state_sharding=opt_state_sharding,
            param_sharding=param_sharding, post_apply=post_apply,
        )
        # device-resident flat gradient pipeline (built lazily from the
        # first boundary's gradient tree; see the constructor docstring)
        self.device_flat = bool(device_flat)
        self.flat_opt_factory = flat_opt_factory
        self._pipeline: Optional[DeviceFlatPipeline] = None
        self._flat_apply_fn = None
        self._flat_apply_spec = None
        self._flat_apply_failed = False
        # (round_id, device ok scalar) of the most recent guarded apply:
        # fetched lazily at the NEXT boundary so the NaN verdict never
        # stalls the dispatch stream (the legacy host-synced check cost a
        # full device round-trip per global step)
        self._pending_apply_ok: Optional[Tuple[str, Any]] = None
        self._lock = threading.Lock()
        # the state backup (device_get of params+opt_state) runs on this
        # thread, OFF the critical path: it is read-only w.r.t. the next
        # round's gradients, so the next accumulation phase overlaps it
        # (SURVEY.md §7 hard-part b; seam cost published in BASELINE.md)
        self._backup_thread: Optional[threading.Thread] = None
        # the backup transfer may use at most this fraction of wall time, so
        # a slow device↔host link (e.g. a tunneled dev chip: ~10 MB/s vs
        # PCIe's GB/s) degrades to periodic backups instead of serializing
        # every global step behind a full state download
        self.backup_duty_cycle = 0.5
        self._backup_done_at = 0.0
        self._backup_took = 0.0
        # jit↔host seam telemetry (ms, last global step)
        self.seam_ms: Dict[str, float] = {}
        self._desynced = False
        self._round_failures = 0
        self.max_round_retries = 2
        # staleness tolerance: a peer that slipped at most this many steps
        # behind ADOPTS the global counter and keeps contributing gradients
        # (computed on slightly-stale params — the bias is bounded and its
        # averaging weight is its sample count); only a larger gap, or an
        # explicit desync, triggers the full state download. Without this a
        # slow volunteer in a fast collaboration lives in a resync loop: the
        # download takes longer than the fast peer's round period, so it
        # re-enters catch-up forever and never computes (round-5 sweep).
        self.resync_step_gap = 8
        self._aux_misses = 0
        self._aux_withheld_at = 0.0
        # contribution-ledger counters (telemetry/ledger.py): cumulative over
        # this peer's lifetime, NOT zeroed at global steps (claim records are
        # last-write-wins per signed subkey, so they must be monotone)
        self.ledger_claims = bool(ledger_claims)
        self.claim_period = float(claim_period)
        self.contrib_samples_total = 0
        self.contrib_rounds_total = 0
        self._last_claim_t = 0.0

    # ------------------------------------------------------------ properties

    @property
    def collaboration_state(self) -> CollaborationState:
        return self.tracker.fetch_collaboration_state()

    @property
    def is_synchronized(self) -> bool:
        return self.local_step >= self.collaboration_state.optimizer_step

    # ------------------------------------------------------------------ step

    def step(
        self,
        state: TrainState,
        grad_acc,
        n_acc,
        samples: int,
    ) -> Tuple[TrainState, Any, Any, bool]:
        """Per-accumulation-boundary call. Returns (state, grad_acc, n_acc,
        performed_global_step). All heavy work happens only when the global
        target batch is reached."""
        assert not self.auxiliary, "auxiliary peers must use step_aux()"
        with self._lock:
            tele = telemetry.resolve(self.telemetry)
            if tele is not None and samples > 0:
                # accumulation-boundary trace; samples == 0 is a retry poll
                # while a round assembles, not a boundary
                tele.counter("opt.boundaries").inc()
            self.local_samples_accumulated += samples
            self.contrib_samples_total += samples
            if self._ema_started:
                # samples == 0 is a retry poll while a round assembles —
                # neither progress nor throughput signal (and it must not
                # touch the EMA clock: a resume() here would discard the
                # elapsed interval and inflate samples/sec)
                if samples > 0:
                    self.performance_ema.update(samples)
            else:
                # first call: start the clock only — measuring from resume()
                # to now would seed the EMA with a near-zero interval and
                # publish absurd samples/sec to the DHT (and this also keeps
                # compile time out of throughput stats)
                self.performance_ema.resume()
                self._ema_started = True

            if self._overlap_inflight is not None:
                # overlap ledger: the wall since this peer resumed
                # accumulating was HIDDEN behind the in-flight round — but
                # only up to the moment the round actually finished
                # (accumulation past that point hides nothing)
                now = monotonic_clock()
                if self._overlap_resumed_at is not None:
                    done_at = self._overlap_done_at
                    covered = (min(now, done_at) if done_at is not None
                               else now)
                    self._overlap_hidden_s += max(
                        0.0, covered - self._overlap_resumed_at
                    )
                    self._overlap_resumed_at = None
                if not self._overlap_inflight["future"].done():
                    # a background round is in flight: keep accumulating —
                    # its result applies one boundary late (the overlap
                    # staleness contract, docs/fleet.md). Catch-up/ramp
                    # decisions wait until the round lands.
                    with steps.phase("collab"):
                        self._report(synced=True)
                    self._overlap_resumed_at = monotonic_clock()
                    return state, grad_acc, n_acc, False
                state, grad_acc, n_acc, stepped, applied = (
                    self._harvest_overlap(state, grad_acc, n_acc)
                )
                if applied:
                    return state, grad_acc, n_acc, stepped
                # failed overlapped round: its gradients were restored into
                # the accumulator — fall through to the synchronous path

            with steps.phase("collab"):
                collab = self.tracker.fetch_collaboration_state()
            gap = collab.optimizer_step - self.local_step
            if (
                gap > self.resync_step_gap
                or self._desynced
                # never been synced at all (fresh init joining a live run):
                # stale-tolerance is for peers that HAVE the collaboration's
                # state modulo a few applies, not for random-init params
                or (gap > 0 and self.local_step == 0)
            ):
                # we fell FAR behind (or our last round failed while others
                # averaged) — catch up from peers: full state download
                if tele is not None:
                    tele.counter("opt.catch_ups").inc()
                    tele.event(
                        "opt.catch_up", gap=gap, desynced=self._desynced,
                        local_step=self.local_step,
                    )
                state = self._catch_up(state, collab)
                self._desynced = False
                grad_acc = zeros_like_grads(state.params)
                n_acc = jax.numpy.zeros([], jax.numpy.int32)
                self.local_samples_accumulated = 0
                self._report(synced=True)
                return state, grad_acc, n_acc, False
            if gap > 0:
                # mildly stale: adopt the counter and KEEP the accumulated
                # gradients — contribute them to the current round instead
                # of burning a state download that outlasts the fast peer's
                # round period (the resync-loop failure mode; see
                # resync_step_gap above). Our params lag by <= gap applies;
                # the gradient bias is bounded and weighted by our samples.
                self.local_step = collab.optimizer_step

            with steps.phase("collab"):
                self._report(synced=True)
            if not collab.ready_for_step:
                return state, grad_acc, n_acc, False

            # decide the round shape on a FORCED-fresh view: the cached view
            # can lag a just-joined peer, and the solo fast path below must
            # not fire while a partner is mid-round
            with steps.phase("collab"):
                collab = self.tracker.fetch_collaboration_state(force=True)
            if collab.optimizer_step > self.local_step:
                self.local_step = collab.optimizer_step  # raced again: rejoin
            if not collab.ready_for_step:
                return state, grad_acc, n_acc, False
            return self._global_step(state, grad_acc, n_acc, collab)

    def _report(self, synced: bool) -> None:
        self.tracker.report_local_progress(
            LocalProgress(
                step=self.local_step,
                # flight-committed samples stay advertised at this step:
                # they are real contribution to the round in progress
                samples_accumulated=(
                    self.local_samples_accumulated
                    + self._overlap_committed_samples
                ),
                samples_per_second=self.performance_ema.samples_per_second,
                time=get_dht_time(),
                client_mode=self.client_mode,
                loss=self._last_loss,
            )
        )
        if self.ledger_claims:
            now = get_dht_time()
            if now - self._last_claim_t >= self.claim_period:
                self._last_claim_t = now
                # claim expiry spans many claim periods so a peer that goes
                # quiet stays creditable until the next coordinator fold
                self.averager.publish_contribution_claim(
                    self.contrib_samples_total,
                    self.contrib_rounds_total,
                    max(0.0, now - self._created_at),
                    expiration=self.claim_period * 10.0,
                )

    # --------------------------------------------- contribution ramp / gate

    def report_loss(self, loss: float) -> None:
        """Advertise this peer's recent training loss on its next progress
        report. Free for callers that already sync a loss scalar per global
        step (both roles do, for logging); feeds the trunk-health gate —
        without a reported loss the gate never engages for this peer."""
        self._last_loss = float(loss)

    @staticmethod
    def ramp_fraction(rounds_since_join: int, ramp_rounds: int) -> float:
        """Contribution-ramp schedule: the fraction of its full sample-count
        weight a peer mixes in on its (rounds_since_join+1)-th round. Linear
        from 1/(ramp_rounds+1) (near-zero for long ramps) to 1.0."""
        if ramp_rounds <= 0:
            return 1.0
        return min(1.0, (rounds_since_join + 1) / (ramp_rounds + 1))

    def mixing_weight_scale(self, collab) -> float:
        """Scale applied to the sample-count weight this peer CONTRIBUTES to
        the group average (it always receives the full group result):

        - contribution ramp: fresh joiners mix at ``ramp_fraction`` of their
          weight until ``ramp_rounds`` global steps have completed;
        - trunk-health gate: a peer whose advertised loss exceeds
          ``health_gate_loss_ratio`` x the median of the OTHER trainers'
          advertised losses defers mixing entirely (weight 0) — its params
          are suspect and must not steer the trunk; it keeps adopting the
          group's averaged direction until its loss rejoins the pack. The
          multiplicative ratio is only meaningful for POSITIVE losses
          (MLM/SwAV); with a zero/negative median the comparison would
          invert (every at-median peer would gate itself and the whole
          collaboration could stall at total weight 0), so the gate
          disengages there.
        """
        scale = self.ramp_fraction(self._rounds_since_join, self.ramp_rounds)
        if (
            self.health_gate_loss_ratio > 0
            and self._last_loss is not None
            and np.isfinite(collab.median_other_loss)
            and collab.median_other_loss > 0
            and self._last_loss
            > self.health_gate_loss_ratio * collab.median_other_loss
        ):
            if self.verbose:
                logger.warning(
                    f"trunk-health gate: local loss {self._last_loss:.4f} > "
                    f"{self.health_gate_loss_ratio:g} x median "
                    f"{collab.median_other_loss:.4f} — deferring mixing "
                    "(contributing zero weight this round)"
                )
            scale = 0.0
        return scale

    def _drop_gated_grads(self, state: TrainState, round_id: str):
        """The trunk-health gate judged this round's gradients unsafe to MIX
        — they are equally unsafe to apply locally (and a lagging partner
        would then resync FROM our diverged post-apply state): drop them and
        schedule a state resync instead of forcing progress."""
        if self.verbose:
            logger.warning(
                f"{round_id}: health-gated and no group average received — "
                "dropping local grads, will resync"
            )
        self._desynced = True
        self._round_failures = 0
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            # applied-vs-dropped ledger: the swarm-health view surfaces a
            # peer whose gradients keep getting discarded
            tele.counter("opt.grads_dropped").inc()
            tele.event(
                "opt.grads_dropped", round_id=round_id,
                samples=self.local_samples_accumulated, reason="health_gate",
            )
        self.local_samples_accumulated = 0
        return (
            state,
            zeros_like_grads(state.params),
            jax.numpy.zeros([], jax.numpy.int32),
            False,
        )

    def _global_step(self, state: TrainState, grad_acc, n_acc, collab):
        """Average gradients with the group and apply one optimizer update."""
        round_id = f"step{collab.optimizer_step}"
        n = max(int(jax.device_get(n_acc)), 1)
        # contribution cap: sample-weighted averaging assumes equal
        # per-sample gradient quality, so the cap scales with OUR samples
        # per MICRO-batch (the contribution is grad_acc/n_acc, a
        # per-micro-batch mean) — it self-calibrates across peer batch
        # sizes, never binds a healthy peer, and suppresses the tiny-batch
        # sinkhorn-noise outlier (measured 19x per-sample energy at B=2;
        # see core/config.py). The mean division, the global-norm reduce
        # and the scale all run as ONE fused device program — either
        # inside the flat pipeline's prepare or via _fused_mean_clip.
        cap = 0.0
        if self.contrib_clip_per_sample > 0:
            cap = self.contrib_clip_per_sample * max(
                float(self.local_samples_accumulated) / n, 1.0
            )

        alone_grace = (
            get_dht_time() - self._created_at
            >= self.tracker.metadata_expiration
        )
        # contribution ramp + trunk-health gate: scale the weight this peer
        # MIXES IN (it still receives the full group average) — a fresh or
        # diverged joiner must not steer a formed trunk (docs/fleet.md)
        weight_scale = self.mixing_weight_scale(collab)
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            # every ramp/gate decision is a trace event: the operator can
            # replay exactly when a joiner reached full weight or a diverged
            # peer was gated out of the mix
            gated = weight_scale == 0.0
            tele.gauge("opt.weight_scale").set(weight_scale)
            if gated:
                tele.counter("opt.gate_engaged").inc()
            tele.event(
                "opt.weight_decision", round_id=round_id,
                scale=weight_scale, gated=gated,
                rounds_since_join=self._rounds_since_join,
                loss=self._last_loss,
            )
        if (
            collab.num_peers_near_step <= 1
            and not self.client_mode
            and alone_grace
        ):
            if weight_scale == 0.0:
                # health-gated with no joinable group: the solo apply would
                # commit the very gradients the gate judged unsafe — and
                # the lagging partners would then resync FROM our diverged
                # post-apply state
                return self._drop_gated_grads(state, round_id)
            # alone AT THIS STEP: the group all-reduce is the identity, so
            # the gradients never leave the device — no device_get, no wire
            # codec, no matchmaking window. A peer that joins later (or
            # catches back up) shows up in the tracker at our step and the
            # next boundary takes the full averaging path. Keying off
            # num_peers_at_step (not num_peers) matters in fast
            # collaborations: a partner that fell behind and is mid-resync
            # CANNOT join this round — waiting a straggler window + burning
            # averaging timeouts on it stalls the whole collaboration
            # (round-5 window sweep, docs/fleet.md), and solo-applying is
            # safe since the lagging peer pulls OUR post-apply state anyway.
            # (The reference pays hivemind's full round machinery even solo;
            # this is the TPU-native win of keeping the apply on-device.)
            #
            # The grace period guards the cold-start race: any peer that was
            # alive recently still has an unexpired progress record (so
            # num_peers > 1), but a peer started in the last few seconds may
            # not have a visible record yet — until one full record lifetime
            # has passed, take the networked path, whose straggler window
            # lets a concurrent starter pair with us.
            self.seam_ms.pop("grads_device_get", None)
            return self._apply_and_advance(
                state, _fused_mean_clip(grad_acc, n, cap), collab,
                group_size=1,
            )

        pipeline = self._ensure_pipeline(grad_acc)
        lossy_d2h = False
        fetch = None
        if pipeline is not None:
            # device-resident seam: ONE fused program computes the mean,
            # the clip reduce, the error-feedback fold and (under a lossy
            # wire format) the quantization, then streams the compressed
            # buffer to the host in async chunks. The boundary only pays
            # the program LAUNCH here — the transfer itself resolves
            # inside the averaging round, overlapped with matchmaking (and
            # with the next micro-batches' accumulation in overlap mode).
            use_ef = weight_scale > 0 and self.error_feedback.enabled
            t0 = time.perf_counter()
            with steps.phase("grad_flatten"):
                fetch = pipeline.fetch(
                    grad_acc, n=n, clip_cap=cap if cap > 0 else None,
                    use_ef=use_ef,
                )
            self.seam_ms["grads_device_get"] = (
                (time.perf_counter() - t0) * 1e3
            )
            contrib = fetch
            ef_commit = (
                (lambda: pipeline.commit(fetch)) if use_ef else None
            )
            lossy_d2h = pipeline.ef_enabled
            if use_ef and tele is not None:
                tele.gauge("opt.ef_residual_norm").set(
                    pipeline.residual_norm()
                )
        else:
            # legacy host seam (non-float leaves refused the pipeline):
            # per-leaf device_get + host flatten + host error feedback
            mean_grads = _fused_mean_clip(grad_acc, n, cap)
            t0 = time.perf_counter()
            with steps.phase("grad_flatten"):
                # device_get of the full grad tree (the jit↔host seam)
                named = _tree_to_named(mean_grads)
            self.seam_ms["grads_device_get"] = (
                (time.perf_counter() - t0) * 1e3
            )
            # error feedback (collaborative/error_feedback.py): fold the
            # last round's quantization residual into this round's
            # contribution so a lossy wire format doesn't bias the trunk.
            # Committed only when the round actually lands — a retried
            # round re-derives the same contribution instead of
            # compounding the residual.
            if weight_scale > 0 and self.error_feedback.enabled:
                contrib, ef_commit = self.error_feedback.prepare(named)
                if tele is not None:
                    tele.gauge("opt.ef_residual_norm").set(
                        self.error_feedback.residual_norm()
                    )
            else:
                contrib, ef_commit = named, None

        # partners CERTAIN to be joinable (reported exactly our step) get
        # the full straggler window; partners merely NEAR (one behind —
        # usually a just-applied record that hasn't refreshed, possibly a
        # peer stuck retrying the previous round) get a short grace only:
        # a genuinely-arriving partner shows up within ~2 refresh periods,
        # and a stuck one must not hold the collaboration hostage for a
        # window + averaging timeout per step (round-5 sweep, docs/fleet.md)
        partners_certain = collab.num_peers_at_step > 1
        near_grace = min(
            self.averager.averaging_expiration,
            max(2.0, 2.0 * self.tracker.default_refresh_period),
        )
        expected_size = (
            collab.num_peers_near_step + collab.num_aux
            if collab.num_peers_near_step >= 2 else None
        )
        window = None if partners_certain else near_grace

        if self._overlap_allowed(weight_scale):
            # restore material for a failed overlapped round: with the
            # device pipeline the RAW accumulator tree stays on device (the
            # restore is then a device-side add, no host round-trip); the
            # legacy path keeps the host named copy as before
            restore = (
                ("acc", grad_acc, n_acc) if pipeline is not None
                else ("named", named, n)
            )
            return self._launch_overlap(
                state, restore, contrib, ef_commit, collab,
                weight_scale, expected_size, window, partners_certain,
                n_micro=n, lossy_d2h=lossy_d2h,
            )

        self.performance_ema.pause()
        try:
            wire_start = monotonic_clock()
            averaged, group_size = self._sync_averager_step(
                contrib, weight_scale, round_id, expected_size, window,
            )
            if averaged is not None and not isinstance(averaged, dict):
                # an averager (or test stub) that echoed the FlatFetch
                # contribution back unresolved: resolve it here
                averaged = averaged.result()
            wire_wall = max(0.0, monotonic_clock() - wire_start)
            # phase attribution stays DISJOINT: the averaging round's wall
            # splits into the exposed remainder of the D2H stream (the
            # transfer resolves inside the round, overlapped with
            # matchmaking — only what matchmaking did NOT cover is a real
            # stall, ~0 on the loopback harness) and the wire round proper
            exposed_d2h = (
                min(fetch.exposed_wait_s, wire_wall)
                if fetch is not None else 0.0
            )
            steps.add("avg_wire", wire_wall - exposed_d2h)
            if fetch is not None:
                steps.add("d2h_stream", exposed_d2h)
            if self.overlap_averaging and tele is not None:
                # overlap ledger, synchronous-fallback form: this round ran
                # on the trainer's critical path (cooldown after a failed
                # overlapped round, ramp, gate, desync) — its entire wall is
                # EXPOSED stall, efficiency 0 (docs/observability.md)
                tele.counter("opt.overlap_exposed_s").inc(wire_wall)
                tele.gauge("opt.overlap_efficiency").set(0.0)
                tele.event(
                    "opt.overlap_ledger", round_id=round_id, mode="sync",
                    hidden_s=0.0, exposed_s=wire_wall, efficiency=0.0,
                )
            contributors = getattr(
                self.averager, "last_contributors", group_size
            )
            if (averaged is not None and contributors <= 1
                    and partners_certain):
                # nobody else CONTRIBUTED gradients while partner trainers
                # exist AT OUR STEP — a singleton group, or a group of just
                # us + aux donors (zero weight): the partners may be
                # averaging without us this round, and applying our local
                # grads now would diverge the replicas. Treat it as a failed
                # round — the retry keeps the grads; repeated misses fall
                # back to local-apply + resync below. (Near-step-only rounds
                # skip this: a peer one behind is on the PREVIOUS round id,
                # so nobody can be averaging round N without us.)
                averaged = None
            if averaged is not None:
                self._round_failures = 0
                if ef_commit is not None:
                    self._settle_error_feedback(
                        ef_commit, group_size, lossy_d2h
                    )
                if not isinstance(averaged, FlatTree):
                    # a plain named dict (legacy/stubbed averager): rebuild
                    # the params-shaped tree here so _apply_and_advance can
                    # tell it apart from a device gradient tree
                    averaged = _named_to_tree(
                        averaged, zeros_like_grads(state.params)
                    )
                return self._apply_and_advance(
                    state, averaged, collab, group_size
                )
            elif partners_certain:
                self._round_failures += 1
                if self._round_failures <= self.max_round_retries:
                    # better than the reference's local-apply: KEEP the
                    # accumulated gradients and retry the round — no
                    # divergence, no wasted samples (one straggler window
                    # lost instead)
                    if self.verbose:
                        logger.warning(
                            f"{round_id}: averaging failed "
                            f"({self._round_failures}/{self.max_round_retries})"
                            " — keeping grads, will retry"
                        )
                    return state, grad_acc, n_acc, False
                # repeated failures: apply local grads to make progress, and
                # schedule a state pull since our params will diverge
                self._desynced = True
                self._round_failures = 0
                if self.verbose and weight_scale > 0.0:
                    logger.warning(
                        f"{round_id}: averaging failed repeatedly — applying "
                        "local grads, will resync"
                    )
            if weight_scale == 0.0:
                # no group average received this round (retry budget spent,
                # or a near-step-only round that came back empty): a
                # health-gated peer has nothing safe to apply locally
                return self._drop_gated_grads(state, round_id)
            # local-apply fallback: OUR mean gradients (clip applied, no
            # residual fold, never quantized) — exactly what the legacy
            # path applied here; the device tree never left the chip
            return self._apply_and_advance(
                state, _fused_mean_clip(grad_acc, n, cap), collab,
                group_size,
            )
        finally:
            self.performance_ema.resume()

    def _sync_averager_step(
        self, contrib, weight_scale, round_id, expected_size, window,
    ):
        """The synchronous averaging round (the ``avg_wire`` step phase).

        ``expected_size`` is the tracker's live peer count: full group =>
        assemble the moment the last partner joins; the straggler window
        then only pays off when peers are genuinely late. Aux peers publish
        presence records and are counted — without them a full group
        assembles the instant the last TRAINER joins and aux donors
        systematically lose the race. During cold start (num_peers <= 1:
        our own record may be the only visible one) the full window is kept
        so a concurrent starter can still pair with us — the design the
        solo-grace path depends on. Only near-step trainers are counted —
        lagging peers are resyncing and must not size the group."""
        return self.averager.step(
            contrib,
            weight=float(self.local_samples_accumulated) * weight_scale,
            round_id=round_id,
            expected_size=expected_size,
            window=window,
        )

    def _settle_error_feedback(
        self, ef_commit, group_size: int, lossy_d2h: bool = False
    ) -> None:
        """A round whose result we adopted settles the pending residual.

        ``group_size > 1``: the contribution crossed the lossy wire — adopt
        this round's quantization error as the next residual.

        ``lossy_d2h`` (device-flat pipeline under a lossy wire format): the
        contribution was quantized ON DEVICE, so even a SINGLETON round has
        crossed the lossy leg — the value we adopted is the dequantized
        form, and its residual must be committed regardless of group size.

        A legacy singleton round never touches any codec: the averager
        hands the contribution tree back verbatim, so grad + residual was
        applied at FULL precision — the carried residual is consumed, and
        committing the phantom wire error there would re-inject it next
        round (the exact bias error feedback exists to remove)."""
        if lossy_d2h or group_size > 1:
            ef_commit()
        else:
            self.error_feedback.reset()

    # ------------------------------------------- device-resident flat seam

    def _ensure_pipeline(self, grad_acc) -> Optional[DeviceFlatPipeline]:
        """The device-flat pipeline for this gradient schema, or None when
        disabled / refused (non-float leaves) — the boundary then takes the
        legacy per-leaf host path."""
        if not self.device_flat:
            return None
        if self._pipeline is not None and self._pipeline.matches_tree(
            grad_acc
        ):
            return self._pipeline
        try:
            self._pipeline = DeviceFlatPipeline.for_tree(
                grad_acc,
                compression=self.averager.compression.value,
                telemetry_registry=self.telemetry,
            )
        except ValueError as e:
            logger.warning(
                f"device-flat pipeline refused this gradient tree ({e}); "
                "falling back to the host flatten path"
            )
            self.device_flat = False
            self._pipeline = None
        return self._pipeline

    def _ensure_flat_apply(self, state: TrainState, spec):
        """The fused flat apply for ``spec``, or None (per-leaf guarded
        apply) when no factory was wired, a sharded layout is in play, or
        a previous build failed."""
        if (
            self.flat_opt_factory is None
            or self._flat_apply_failed
            or self.mesh is not None
            or self.opt_state_sharding is not None
            or self.param_sharding is not None
        ):
            return None
        key = [(name, tuple(shape)) for name, shape, _dtype in spec]
        if self._flat_apply_fn is not None and self._flat_apply_spec == key:
            return self._flat_apply_fn
        try:
            flat_tx = self.flat_opt_factory(spec, state.params)
            self._flat_apply_fn = make_flat_apply_step(
                flat_tx, spec, post_apply=self.post_apply
            )
            self._flat_apply_spec = key
        except Exception as e:  # noqa: BLE001 — a flat-apply build failure
            # must degrade to the per-leaf chain, never kill training
            logger.warning(
                f"flat apply unavailable ({e!r}); keeping the per-leaf "
                "guarded apply"
            )
            self._flat_apply_failed = True
            self._flat_apply_fn = None
        return self._flat_apply_fn

    def _check_apply_ok(self, final: bool = False) -> None:
        """Read the PREVIOUS guarded apply's NaN verdict. Called at the
        next boundary (the flag has long settled — reading it then costs
        nothing) and once at shutdown (``final=True``); a rolled-back
        update is logged and counted one boundary late instead of paying a
        host sync on every global step."""
        pending, self._pending_apply_ok = self._pending_apply_ok, None
        if pending is None:
            return
        round_id, ok = pending
        try:
            rolled_back = not bool(ok)
        except Exception:  # noqa: BLE001 — a dead device at shutdown must
            # not mask the real failure
            return
        if rolled_back:
            # NaN guard (CollaborativeCallback.on_step_end semantics,
            # albert/run_trainer.py:134-137): the update was discarded
            # inside the jitted apply
            logger.warning(
                f"{round_id}: non-finite params; update was rolled back"
            )
            tele = telemetry.resolve(self.telemetry)
            if tele is not None:
                tele.counter("opt.nan_rollbacks").inc()
                tele.event("opt.nan_rollback", round_id=round_id)

    # ------------------------------------------------- background averaging

    def _overlap_allowed(self, weight_scale: float) -> bool:
        """Overlap mode launches a background round only when the peer is a
        full-standing contributor: never during the contribution ramp (a
        joiner's weight schedule must advance one observed round at a time),
        never while health-gated (a gated round's result decides whether the
        local grads are even safe to keep), never while desynced or cooling
        down from a failed overlapped round — those boundaries take the
        synchronous path with its retry/resync ladder."""
        return (
            self.overlap_averaging
            and not self._overlap_cooldown
            and not self.auxiliary
            and not self._desynced
            and weight_scale > 0.0  # trunk-health gate engaged => sync path
            and self._rounds_since_join >= self.ramp_rounds  # ramp finished
        )

    def _launch_overlap(
        self, state: TrainState, restore, contrib, ef_commit, collab,
        weight_scale, expected_size, window, partners_certain, n_micro,
        lossy_d2h=False,
    ):
        """Start the averaging round on the DHT executor and hand control
        straight back to the trainer: the next accumulation phase overlaps
        matchmaking + the full wire round — and, with the device pipeline,
        the gradient D2H stream itself (the transfer resolves inside the
        round while the trainer accumulates). The contributed samples are
        committed to the in-flight round (accumulators reset); the averaged
        update lands at a later boundary — one boundary of staleness, by
        contract. ``restore`` is either ("acc", grad_acc, n_acc) — the raw
        device accumulators, restored by a device-side add on failure — or
        the legacy ("named", host_mean_tree, n_micro)."""
        round_id = f"step{collab.optimizer_step}"
        fut = self.averager.step(
            contrib,
            weight=float(self.local_samples_accumulated) * weight_scale,
            round_id=round_id,
            return_future=True,
            expected_size=expected_size,
            window=window,
        )
        # overlap ledger: round wall runs launch → future completion; the
        # done-callback stamps completion on the resolving thread so a round
        # that lands BETWEEN boundaries is not credited with hiding the
        # accumulation that ran after it finished
        self._overlap_launched_at = monotonic_clock()
        self._overlap_hidden_s = 0.0
        self._overlap_done_at = None

        def _stamp_done(_f) -> None:
            self._overlap_done_at = monotonic_clock()

        add_done = getattr(fut, "add_done_callback", None)
        if add_done is not None:
            add_done(_stamp_done)
        self._overlap_inflight = {
            "future": fut,
            "restore": restore,  # pre-error-feedback material for failure
            "commit": ef_commit,
            "collab": collab,
            "samples": self.local_samples_accumulated,
            "n_micro": int(n_micro),
            "partners_certain": partners_certain,
            "lossy_d2h": lossy_d2h,
        }
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("opt.overlap_launched").inc()
            tele.event(
                "opt.overlap_launched", round_id=round_id,
                samples=self.local_samples_accumulated,
            )
        if self.verbose:
            logger.info(
                f"{round_id}: averaging launched in background "
                f"({self.local_samples_accumulated} samples committed)"
            )
        self._overlap_committed_samples = self.local_samples_accumulated
        self.local_samples_accumulated = 0
        # from here the trainer accumulates concurrently with the round —
        # the ledger credits launch→next-boundary wall as hidden time
        self._overlap_resumed_at = monotonic_clock()
        return (
            state,
            zeros_like_grads(state.params),
            jax.numpy.zeros([], jax.numpy.int32),
            False,
        )

    def _harvest_overlap(self, state: TrainState, grad_acc, n_acc):
        """The in-flight round resolved. On success, apply its averaged
        update (one boundary late) while PRESERVING the microbatches
        accumulated during the flight. On failure, restore the committed
        gradients into the live accumulator and let this boundary take the
        synchronous path. Returns (state, grad_acc, n_acc, stepped,
        applied)."""
        inflight, self._overlap_inflight = self._overlap_inflight, None
        # the flight resolved either way: on success the step advances (the
        # committed samples were consumed by the applied round), on failure
        # they are restored into the live accumulator below — keeping the
        # committed count advertised past this point would double-count
        self._overlap_committed_samples = 0
        collab = inflight["collab"]
        round_id = f"step{collab.optimizer_step}"
        tele = telemetry.resolve(self.telemetry)
        # overlap ledger: hidden = concurrent-accumulation wall credited at
        # each boundary while the round flew (capped at the round wall);
        # exposed = the remainder of launch→finish the compute did NOT
        # cover. A round that landed within one boundary reports
        # efficiency ~1; a round the trainer outpaced reports the stall.
        done_at = self._overlap_done_at
        if done_at is None:
            done_at = monotonic_clock()
        round_wall = max(0.0, done_at - self._overlap_launched_at)
        hidden = min(self._overlap_hidden_s, round_wall)
        exposed = max(0.0, round_wall - hidden)
        self._overlap_hidden_s = 0.0
        self._overlap_done_at = None
        if tele is not None:
            efficiency = hidden / round_wall if round_wall > 0 else 1.0
            tele.counter("opt.overlap_hidden_s").inc(hidden)
            tele.counter("opt.overlap_exposed_s").inc(exposed)
            tele.gauge("opt.overlap_efficiency").set(efficiency)
            tele.event(
                "opt.overlap_ledger", round_id=round_id, mode="overlap",
                hidden_s=hidden, exposed_s=exposed, efficiency=efficiency,
                round_wall_s=round_wall,
            )
        try:
            averaged, group_size = inflight["future"].result()
        except Exception as e:  # noqa: BLE001 — a failed round costs one
            # round, never the training process (AllreduceFailed is already
            # folded into None by the averager; this guards executor deaths)
            logger.warning(f"{round_id}: overlapped round raised {e!r}")
            averaged, group_size = None, 1
        contributors = getattr(self.averager, "last_contributors", group_size)
        if averaged is not None and not isinstance(averaged, dict):
            # an echoed, unresolved FlatFetch contribution (stubs): resolve
            averaged = averaged.result()
        if (averaged is not None and contributors <= 1
                and inflight["partners_certain"]):
            # same replica-divergence guard as the synchronous path: known
            # partners may have averaged without us — do not apply solo
            averaged = None
        if averaged is not None and not isinstance(averaged, FlatTree):
            # legacy named-dict result: validate against the param schema
            # before adopting (a FlatTree from our own averager is already
            # layout-checked)
            try:
                averaged = _named_to_tree(
                    averaged, zeros_like_grads(state.params)
                )
            except (KeyError, ValueError) as e:
                logger.warning(f"{round_id}: overlap result rejected: {e!r}")
                averaged = None
        if averaged is not None:
            # a landed round clears the retry ladder, same as the
            # synchronous success path — otherwise stale failure counts
            # survive overlap successes and a later transient failure
            # skips straight to local-apply + resync
            self._round_failures = 0
            if inflight["commit"] is not None:
                self._settle_error_feedback(
                    inflight["commit"], group_size,
                    inflight.get("lossy_d2h", False),
                )
            if tele is not None:
                tele.counter("opt.overlap_applied").inc()
                tele.event(
                    "opt.overlap_applied", round_id=round_id,
                    group_size=group_size,
                    accumulated_during_flight=self.local_samples_accumulated,
                )
            result = self._apply_and_advance(
                state, averaged, collab, group_size,
                keep_acc=(grad_acc, n_acc),
            )
            return (*result, True)
        # failure: fold the committed gradients back into the accumulator
        # and fall back to the synchronous path — cooldown until a global
        # step succeeds
        self._overlap_cooldown = True
        if tele is not None:
            tele.counter("opt.overlap_failed").inc()
            tele.event("opt.overlap_failed", round_id=round_id)
        if self.verbose:
            logger.warning(
                f"{round_id}: overlapped round failed — restoring grads, "
                "falling back to synchronous averaging"
            )
        restore = inflight["restore"]
        if restore[0] == "acc":
            # device pipeline: the raw accumulators never left the chip —
            # merge them back with one device-side add, no host round-trip
            _tag, old_acc, old_n = restore
            grad_acc = jax.tree.map(lambda a, b: a + b, grad_acc, old_acc)
            n_acc = n_acc + old_n
        else:
            # legacy: mean * n_micro reconstructs the committed sum
            _tag, named, n_micro = restore
            restored = _named_to_tree(
                named, zeros_like_grads(state.params)
            )
            grad_acc = jax.tree.map(
                lambda a, m: a + m * n_micro, grad_acc, restored
            )
            n_acc = n_acc + n_micro
        self.local_samples_accumulated += inflight["samples"]
        return state, grad_acc, n_acc, False, False

    def _apply_and_advance(self, state: TrainState, mean_grads, collab,
                           group_size: int, keep_acc=None):
        """Optimizer apply + NaN guard + backup + progress bookkeeping —
        the tail of a global step, shared by the solo, networked and
        overlap-harvest paths. ``keep_acc=(grad_acc, n_acc)`` preserves the
        accumulation that ran while an overlapped round was in flight
        (those microbatches belong to the NEXT round)."""
        round_id = f"step{collab.optimizer_step}"
        t0 = time.perf_counter()
        with steps.phase("opt_apply"):
            # previous boundary's NaN verdict has settled by now — read it
            # without stalling this boundary's dispatch
            self._check_apply_ok()
            # NaN guard now lives INSIDE the jitted apply (a fused
            # all-finite reduce + jnp.where rollback): no pre-apply HBM
            # copy of (step, params, opt_state), no host-synced finite
            # check per global step (make_guarded_apply_step). post_apply
            # is folded into the same program.
            flat_fn = (
                self._ensure_flat_apply(state, mean_grads.spec)
                if isinstance(mean_grads, FlatTree) else None
            )
            if flat_fn is not None:
                # fused FLAT apply: the averaged result crosses host->device
                # as ONE buffer and the whole optimizer update runs as
                # segment reductions over it (optim/flat.py)
                flat_dev = jax.device_put(mean_grads.flat)
                new_state, ok = flat_fn(state, flat_dev)
            else:
                if isinstance(mean_grads, FlatTree):
                    # flat result without a flat apply: rebuild the
                    # params-shaped tree from the named views (zero-copy)
                    mean_grads = _named_to_tree(
                        mean_grads, zeros_like_grads(state.params)
                    )
                new_state, ok = self._apply_fn(state, mean_grads)
            self._pending_apply_ok = (round_id, ok)
        self.seam_ms["apply"] = (time.perf_counter() - t0) * 1e3
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("opt.grads_applied").inc()
            tele.event(
                "opt.global_step", step=collab.optimizer_step + 1,
                group_size=group_size,
                samples=self.local_samples_accumulated,
            )
        self.local_step = collab.optimizer_step + 1
        self._rounds_since_join += 1  # advances the contribution ramp
        self.contrib_rounds_total += 1  # cumulative, for the signed claim
        self._overlap_cooldown = False  # a landed step re-arms overlap
        if keep_acc is None:
            self.local_samples_accumulated = 0
        self._backup_and_share(new_state)
        with steps.phase("collab"):
            self._report(synced=True)
            self.tracker.fetch_collaboration_state(force=True)
        if self.verbose:
            logger.info(
                f"global step {self.local_step} applied "
                f"(group={group_size}, samples~{collab.samples_accumulated})"
            )
        if keep_acc is not None:
            # overlap harvest: the microbatches accumulated during the
            # flight stay live — they are the next round's contribution
            return new_state, keep_acc[0], keep_acc[1], True
        return (
            new_state,
            zeros_like_grads(new_state.params),
            jax.numpy.zeros([], jax.numpy.int32),
            True,
        )

    # -------------------------------------------------------- state recovery

    def seed_state_sharing(self, state: TrainState) -> None:
        """Publish a state snapshot BEFORE the first global step: a slow
        partner that misses round 0 resyncs immediately instead of finding
        no provider (the first post-apply backup takes tens of seconds on
        slow device→host links) and silently diverging until one appears."""
        self._backup_and_share(state)

    def _backup_and_share(self, state: TrainState) -> None:
        """Host snapshot of (params, opt_state) for late joiners
        (load_state_from_peers counterpart, run_trainer.py:124-128). The
        NaN-rollback backup is NOT here — it lives on device
        (see ``_apply_and_advance``) — so this transfer is pure state
        sharing and can be skipped entirely when sharing is off.

        Runs on a background thread: the transfer is read-only w.r.t. the
        next round (a fresh grad accumulator), so the next accumulation phase
        overlaps the hundreds of MB of device→host traffic instead of
        stalling behind it.

        Duty-cycle cap: when the transfer takes longer than
        ``backup_duty_cycle`` of the time between global steps, skip this
        step's snapshot instead of queueing behind it — late joiners get a
        slightly older state, training throughput stays intact. (On PCIe the
        transfer is ~ms and effectively every step is shared; the cap only
        bites on slow links.)
        """
        if not self.averager.allow_state_sharing:
            return
        if self._backup_thread is not None and self._backup_thread.is_alive():
            return  # previous snapshot still draining; don't stall the step
        now = time.perf_counter()
        idle_needed = self._backup_took * (1.0 / self.backup_duty_cycle - 1.0)
        if now < self._backup_done_at + idle_needed:
            return
        self._join_backup()
        step, local_step = int(state.step), self.local_step
        # snapshot ON DEVICE first (an HBM copy, ~ms): the next global step's
        # apply DONATES state's buffers, so the thread must never hold the
        # live arrays — device_get on a donated buffer would raise "Array has
        # been deleted" mid-transfer on exactly the slow links the duty cycle
        # exists for
        snapshot = jax.tree.map(
            jax.numpy.copy, (state.params, state.opt_state)
        )

        def backup() -> None:
            t0 = time.perf_counter()
            host_state = jax.device_get(snapshot)
            self.averager.set_shared_state(
                _tree_to_named(host_state),
                {"step": step, "local_step": local_step},
            )
            self.averager.publish_state_provider(
                expiration=self.tracker.metadata_expiration * 4,
                step=local_step,
            )
            end = time.perf_counter()
            self._backup_done_at, self._backup_took = end, end - t0
            self.seam_ms["backup"] = (end - t0) * 1e3

        self._backup_thread = threading.Thread(target=backup, daemon=True)
        self._backup_thread.start()

    def _join_backup(self) -> None:
        if self._backup_thread is not None:
            self._backup_thread.join()
            self._backup_thread = None

    def _device_put(self, tree, sharding=None):
        """Host tree -> devices, committed onto the slice mesh (replicated,
        or a caller-supplied sharding pytree e.g. the ZeRO-1 moment layout)
        when one exists so accumulate doesn't re-broadcast per micro-batch."""
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(
                tree, sharding or NamedSharding(self.mesh, P())
            )
        return jax.device_put(tree)

    def load_state_from_peers(
        self, state: TrainState, only_if_newer: bool = False
    ) -> TrainState:
        """Download the newest collaboration state (params+opt) from a peer
        (albert/run_trainer.py:124-128 on_train_begin semantics). Returns the
        local state unchanged if nobody shares yet.

        ``only_if_newer`` — adopt the remote state only when its step is
        STRICTLY deeper than ``self.local_step``. Role startup after a disk
        resume must pass True: a fresh-init partner that raced a few counter
        steps ahead while this peer was still compiling must not beat a
        770-step checkpoint (measured: the resumed peer silently demoted
        itself to the fresh peer's near-random params and the run collapsed).
        Catch-up/resync paths keep the unconditional adopt — a desynced peer
        wants the collaboration's canonical state even at the same step."""
        self._join_backup()
        if only_if_newer:
            # KB-cheap pre-check against the advertised provider steps: a
            # resumed peer usually HAS the deepest state, and downloading a
            # full params+opt blob only to discard it wastes the provider's
            # uplink (advisor r5). The post-download check below still
            # guards the race where the advertisement was newer than the
            # state actually served. An advertisement can itself lag the
            # duty-cycled backup by several applies — so when the TRACKER
            # says the collaboration's counter is already past us, a
            # tied-but-stale advertisement must not skip the download
            # (advisor r5 low #2; the tracker view is equally KB-cheap).
            best = self.averager.best_advertised_state_step()
            tracker_step = self.tracker.fetch_collaboration_state().optimizer_step
            if (
                best is not None
                and best <= self.local_step
                and tracker_step <= self.local_step
            ):
                logger.info(
                    f"best advertised peer state (step {best}) is not newer "
                    f"than local {self.local_step}; keeping local state"
                )
                return state
        result = self.averager.load_state_from_peers()
        if result is None:
            logger.info("no state providers found; starting from local state")
            return state
        metadata, named = result
        remote_step = int(metadata.get("local_step", metadata.get("step", 0)))
        if only_if_newer and remote_step <= self.local_step:
            logger.info(
                f"peer state at global step {remote_step} is not newer than "
                f"local {self.local_step}; keeping local state"
            )
            return state
        template = jax.device_get((state.params, state.opt_state))
        try:
            params, opt_state = _named_to_tree(named, template)
        except (KeyError, ValueError) as e:
            logger.warning(f"peer state incompatible ({e!r}); keeping local")
            return state
        # dedlint: disable=lock-unguarded-mutation — entered either from
        # step() -> _catch_up() with self._lock held, or from the role's
        # join/bootstrap path before the training loop (and its threads)
        # exists; taking the non-reentrant lock here would deadlock the
        # _catch_up path
        self.local_step = remote_step  # dedlint: disable=lock-unguarded-mutation
        new_state = state.replace(
            step=jax.numpy.asarray(int(metadata.get("step", 0)), jax.numpy.int32),
            params=self._device_put(params, self.param_sharding),
            opt_state=self._device_put(opt_state, self.opt_state_sharding),
        )
        logger.info(f"loaded state from peers at global step {self.local_step}")
        return new_state

    def _catch_up(self, state: TrainState, collab) -> TrainState:
        # the carried quantization residual belongs to gradients computed on
        # params we are about to replace — feeding it forward would inject
        # stale signal into the first post-resync round
        self.error_feedback.reset()
        if self._pipeline is not None:
            self._pipeline.reset_residual()
        new_state = self.load_state_from_peers(state)
        # even if nobody shares state, adopt the global step counter so we
        # rejoin the current round instead of contesting old ones
        self.local_step = max(self.local_step, collab.optimizer_step)
        return new_state

    # -------------------------------------------------------------- aux role

    def bootstrap_aux_template(
        self, timeout: float = 60.0
    ) -> Optional[Dict[str, np.ndarray]]:
        """Fetch the GRADIENT tensor shapes from a live state provider, so
        an aux peer can join a collaboration knowing only the DHT peers —
        the reference's aux bootstraps from the collaboration the same way
        (run_aux.py:243-263). Uses the KB-sized schema-only reply, never the
        full state blob. Returns None while nobody shares state yet."""
        schema = self.averager.fetch_state_schema(timeout=timeout)
        if schema is None:
            return None
        # shared state is the flattened (params, opt_state) tuple, so param
        # leaves carry the "[0]" tuple-index prefix (_tree_to_named keystr
        # naming); gradients are params-shaped => strip that prefix. A wrong
        # template still fails cleanly at join time (schema handshake).
        template = {
            k[len("[0]"):]: np.zeros(shape, np.float32)
            for k, shape in schema.items()
            if k.startswith("[0]")
        }
        return template or None

    # consecutive missed rounds after which an aux stops advertising
    # presence: a tracker-visible aux that can never actually reach the
    # averaging groups (e.g. NAT-blocked from every leader) must not make
    # trainers hold the straggler window open for it on every round
    aux_presence_miss_limit = 2

    def _report_aux_presence(self) -> None:
        """Publish a zero-progress presence record so trainers' group
        sizing counts this aux as an expected averaging participant.

        Withheld after ``aux_presence_miss_limit`` consecutive missed
        rounds — but only for a cooldown: once presence is withheld,
        trainers assemble the instant the last trainer joins, which makes
        winning a round (the other re-advertise trigger) a pure race — a
        healthy aux that hit a transient blip must not starve forever.
        After the cooldown it re-advertises and re-probes; a genuinely
        unreachable aux re-withholds two rounds later.

        The record's ``step`` is 0, not ``local_step``: no current consumer
        reads an aux record's step (the tracker filters aux records out of
        the optimizer_step max), and publishing a step that can briefly
        LEAD the trainers' would send any legacy tracker without the aux
        filter into a spurious catch-up loop."""
        if self._aux_misses >= self.aux_presence_miss_limit:
            cooldown = 4.0 * self.tracker.metadata_expiration
            if get_dht_time() - self._aux_withheld_at < cooldown:
                return
            self._aux_misses = 0
        self.tracker.report_local_progress(
            LocalProgress(
                step=0,
                samples_accumulated=0,
                samples_per_second=0.0,
                time=get_dht_time(),
                client_mode=False,
                aux=True,
            )
        )

    def step_aux(self, template: Dict[str, np.ndarray]) -> bool:
        """Auxiliary peer (run_aux.py:260-263): join the current round with
        zero weight, donating bandwidth. ``template`` gives tensor shapes."""
        assert self.auxiliary
        self._report_aux_presence()
        collab = self.tracker.fetch_collaboration_state()
        if not collab.ready_for_step:
            return False
        round_id = f"step{collab.optimizer_step}"
        zeros = {k: np.zeros_like(v) for k, v in template.items()}
        averaged, group_size = self.averager.step(
            zeros, weight=0.0, round_id=round_id
        )
        ok = averaged is not None
        if ok:
            # only a round we actually completed advances our step — a
            # failed round must leave local_step put so the aux retries the
            # SAME round (and its presence record doesn't claim progress
            # it never made)
            # dedlint: disable=lock-unguarded-mutation — auxiliary peers
            # never run step(): local_step is only ever touched by the one
            # aux loop thread, there is no trainer thread to race
            self.local_step = collab.optimizer_step + 1  # dedlint: disable=lock-unguarded-mutation
            self._aux_misses = 0
        else:
            self._aux_misses += 1
            if self._aux_misses == self.aux_presence_miss_limit:
                self._aux_withheld_at = get_dht_time()
        self.tracker.fetch_collaboration_state(force=True)
        return ok

    def shutdown(self) -> None:
        inflight = self._overlap_inflight
        if inflight is not None:
            inflight["future"].cancel()
            self._overlap_inflight = None
        self._check_apply_ok(final=True)
        self._join_backup()
        self.averager.shutdown()
