from dedloc_tpu.collaborative.progress import (
    LocalProgress,
    CollaborationState,
    ProgressTracker,
)
from dedloc_tpu.collaborative.optimizer import CollaborativeOptimizer
