"""Global progress tracking over the DHT.

Capability parity with hivemind CollaborativeOptimizer's collaboration-state
machinery (SURVEY.md §2.6): every peer publishes its local accumulation
progress under ``{prefix}_progress``; the tracker aggregates to a global
sample count, the current global optimizer step, peer counts and an ETA to
the next step; the refresh period adapts between ``min_refresh_period`` and
``max_refresh_period`` based on that ETA (albert/arguments.py:29-41).
"""
from __future__ import annotations

import dataclasses
import math
import statistics
from dataclasses import dataclass
from typing import Optional

from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class LocalProgress:
    step: int
    samples_accumulated: int
    samples_per_second: float
    time: float
    client_mode: bool = False
    # auxiliary peers (run_aux.py capability) publish PRESENCE records:
    # they carry no training progress (zero samples, zero throughput) but
    # let group sizing count the aux as an expected averaging participant —
    # otherwise a leader using the tracker's peer count assembles the
    # instant the last TRAINER joins and the aux systematically loses the
    # race it is there to win
    aux: bool = False
    # most recent training loss this peer advertises (None = not reported):
    # the trunk-health gate compares a peer's own loss against the swarm
    # median to decide whether its gradients are healthy enough to mix
    loss: Optional[float] = None

    def pack(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def unpack(cls, d: dict) -> "LocalProgress":
        loss = d.get("loss")
        return cls(
            step=int(d["step"]),
            samples_accumulated=int(d["samples_accumulated"]),
            samples_per_second=float(d["samples_per_second"]),
            time=float(d["time"]),
            client_mode=bool(d.get("client_mode", False)),
            aux=bool(d.get("aux", False)),
            loss=float(loss) if loss is not None else None,
        )


@dataclass
class CollaborationState:
    optimizer_step: int
    samples_accumulated: int  # collaboration-wide, towards the NEXT step
    target_batch_size: int
    num_peers: int  # trainers only — aux presence is counted separately
    num_clients: int
    eta_next_step: float  # seconds
    next_fetch_time: float  # dht time
    num_aux: int = 0  # live aux peers expected to join averaging rounds
    # trainers whose reported step == optimizer_step: the peers that can
    # certainly JOIN the current round — these get the full straggler
    # window. A peer more than one behind fell out (it is resyncing state)
    # and cannot contribute; sizing groups on it stalls a full window +
    # averaging timeout per step (round-5 window sweep, docs/fleet.md).
    num_peers_at_step: int = 0
    # ...plus peers exactly ONE step behind: usually a partner that just
    # applied the previous round and reports its new step only at its next
    # boundary (progress records are seconds stale) — but possibly one
    # stuck retrying the PREVIOUS round that will never arrive. The leader
    # therefore gives near-step-only rounds a SHORT grace, not the full
    # window: a genuinely-arriving partner shows up within a couple of
    # refresh periods, a stuck one must not hold the collaboration hostage
    # (both failure shapes observed in the round-5 sweep).
    num_peers_near_step: int = 0
    # start the round this many samples EARLY so matchmaking latency
    # overlaps the tail of accumulation (the reference's batch_size_lead,
    # albert/arguments.py CollaborativeOptimizerArguments)
    batch_size_lead: int = 0
    # median advertised loss of the OTHER live trainers (nan when nobody
    # advertises one): the reference point for the trunk-health gate — a
    # peer whose own loss diverges far above this defers mixing
    median_other_loss: float = float("nan")

    @property
    def ready_for_step(self) -> bool:
        return (
            self.samples_accumulated
            >= self.target_batch_size - self.batch_size_lead
        )


class ProgressTracker:
    def __init__(
        self,
        dht: DHT,
        prefix: str,
        peer_subkey: bytes,
        target_batch_size: int,
        min_refresh_period: float = 0.5,
        max_refresh_period: float = 30.0,
        default_refresh_period: float = 3.0,
        metadata_expiration: float = 30.0,
        expected_drift_peers: float = 3.0,
        expected_drift_rate: float = 0.2,
        batch_size_lead: int = 0,
    ):
        if not 0 <= batch_size_lead < target_batch_size:
            # lead >= target would make every step ready at zero samples —
            # a busy-loop of zero-gradient optimizer steps; fail at startup
            raise ValueError(
                f"batch_size_lead ({batch_size_lead}) must be in "
                f"[0, target_batch_size={target_batch_size})"
            )
        self.dht = dht
        self.key = f"{prefix}_progress"
        self.peer_subkey = peer_subkey
        self.target_batch_size = target_batch_size
        self.batch_size_lead = batch_size_lead
        self.min_refresh_period = min_refresh_period
        self.max_refresh_period = max_refresh_period
        self.default_refresh_period = default_refresh_period
        self.metadata_expiration = metadata_expiration
        self.expected_drift_peers = expected_drift_peers
        self.expected_drift_rate = expected_drift_rate
        self._records: Optional[dict] = None  # subkey -> LocalProgress (DHT view)
        self._next_fetch: float = 0.0
        self._last_local: Optional[LocalProgress] = None

    def report_local_progress(self, progress: LocalProgress) -> None:
        """Fire-and-forget publish of this peer's accumulation state."""
        self._last_local = progress
        try:
            self.dht.store(
                self.key,
                progress.pack(),
                get_dht_time() + self.metadata_expiration,
                subkey=self.peer_subkey,
                return_future=True,  # don't block the training thread
            )
        except Exception as e:  # noqa: BLE001 — progress is best-effort
            logger.debug(f"progress report failed: {e!r}")

    def fetch_collaboration_state(self, force: bool = False) -> CollaborationState:
        """Aggregate everyone's progress.

        Remote records are cache-gated by the adaptive refresh period, but
        this peer's OWN latest progress is overlaid on every call — like the
        reference, a peer that accumulates the whole target batch by itself
        becomes ready_for_step immediately, without waiting for its own DHT
        write to round-trip or the refresh deadline to pass."""
        now = get_dht_time()
        fetched = False
        if force or self._records is None or now >= self._next_fetch:
            entry = self.dht.get(self.key, latest=True)
            by_subkey: dict = {}
            if entry is not None and hasattr(entry.value, "items"):
                for sk, v in entry.value.items():
                    try:
                        by_subkey[sk] = LocalProgress.unpack(v.value)
                    except Exception:  # noqa: BLE001 — malformed record
                        continue
            self._records = by_subkey
            fetched = True

        by_subkey = dict(self._records)
        if self._last_local is not None:
            stored = by_subkey.get(self.peer_subkey)
            if stored is None or stored.time <= self._last_local.time:
                by_subkey[self.peer_subkey] = self._last_local

        # aux presence records carry no training progress — they must not
        # drive optimizer_step (an aux's step can lead trainers briefly
        # around a round boundary, and letting it win the max would make
        # every trainer think it fell behind) nor the sample/throughput
        # totals; they only size averaging groups (num_aux)
        records = [r for r in by_subkey.values() if not r.aux]
        num_aux = sum(r.aux for r in by_subkey.values())
        # trunk-health reference: median advertised loss of the OTHER
        # trainers (own record excluded — with two peers, including self
        # would drag the median halfway toward the diverged joiner and
        # soften the very gate it feeds)
        other_losses = [
            r.loss
            for sk, r in by_subkey.items()
            if not r.aux
            and sk != self.peer_subkey
            and r.loss is not None
            and math.isfinite(r.loss)
        ]
        median_other_loss = (
            statistics.median(other_losses) if other_losses else float("nan")
        )
        max_step, total_samples, total_sps = 0, 0, 0.0
        num_peers = num_clients = num_at_step = num_near = 0
        if records:
            max_step = max(r.step for r in records)
        for r in records:
            num_peers += 1
            num_clients += bool(r.client_mode)
            total_sps += r.samples_per_second
            if r.step == max_step:
                total_samples += r.samples_accumulated
                num_at_step += 1
            if r.step >= max_step - 1:
                num_near += 1
        # throughput below the floor means "not yet measured" (a fresh peer's
        # EMA), NOT a multi-year ETA — treat the ETA as unknown so the refresh
        # period falls back to the default instead of pinning at the maximum
        # ETA to the READY point — target minus lead, so the adaptive poll
        # cadence tightens in time to catch the (earlier) round start
        eta = (
            max(
                0.0,
                self.target_batch_size - self.batch_size_lead - total_samples,
            ) / total_sps
            if num_peers and total_sps > 1e-6
            else float("inf")
        )
        if fetched:
            # adaptive refresh (arguments.py:29-41): poll faster near the step
            period = min(
                self.max_refresh_period,
                max(self.min_refresh_period, eta / 2 if eta != float("inf")
                    else self.default_refresh_period),
            )
            self._next_fetch = now + period
        return CollaborationState(
            optimizer_step=max_step,
            samples_accumulated=total_samples,
            target_batch_size=self.target_batch_size,
            num_peers=num_peers,
            num_clients=num_clients,
            num_aux=num_aux,
            num_peers_at_step=num_at_step,
            num_peers_near_step=num_near,
            eta_next_step=eta,
            next_fetch_time=self._next_fetch,
            batch_size_lead=self.batch_size_lead,
            median_other_loss=median_other_loss,
        )
