"""The signed metrics bus: per-peer training metrics over the DHT.

Capability parity with albert/metrics_utils.py:9-24: a pydantic
``LocalMetrics`` schema stored under ``{prefix}_metrics`` with one subkey per
peer, protected by RSA signature + schema validation so metrics are
spoof-resistant. The coordinator (roles/coordinator.py) aggregates these the
same way run_first_peer.py:176-218 does.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from pydantic import BaseModel, StrictFloat, StrictInt, conint

from dedloc_tpu.core.serialization import unpack_obj
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.crypto import RSAPrivateKey
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.dht.validation import (
    RecordValidatorBase,
    RSASignatureValidator,
    SchemaValidator,
)
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class LocalMetrics(BaseModel):
    """Reference: LocalMetrics(BaseModel) at albert/metrics_utils.py:9-15.

    The optional telemetry tail is the vissl PerfStats capability
    (vissl/utils/perf_stats.py:12-249) carried over the metrics bus: per-peer
    step-phase timings + HBM occupancy, aggregated by the coordinator into
    its JSONL. Optional so peers without telemetry enabled (and round-1
    records) still validate."""

    step: StrictInt
    samples_per_second: StrictFloat
    samples_accumulated: StrictInt
    loss: StrictFloat
    mini_steps: StrictInt
    step_time_ms: Optional[StrictFloat] = None  # accumulation-boundary wall
    data_wait_ms: Optional[StrictFloat] = None  # host input-pipeline stall
    allreduce_ms: Optional[StrictFloat] = None  # averaging round (stepped only)
    hbm_bytes: Optional[StrictInt] = None  # device bytes_in_use
    # swarm-telemetry snapshot (telemetry/registry.py Telemetry.snapshot):
    # flat {counter/gauge/histogram name: value} — the coordinator folds
    # these into its swarm-health record (telemetry/health.py). Optional so
    # peers with telemetry disabled (and pre-telemetry records) validate.
    # Per-link estimates ride the same dict as "link.<host:port>.<field>"
    # keys (telemetry/links.py), bounded to the busiest top-K links.
    telemetry: Optional[Dict[str, float]] = None
    # this peer's advertised RPC endpoint ("host:port"): lets the
    # coordinator resolve the link destinations OTHER peers report into
    # peer labels when folding the swarm topology record. Optional so
    # pre-link-telemetry records (and client-mode peers) validate.
    endpoint: Optional[str] = None
    # filled by fetch_metrics from the signed DHT subkey, never by peers:
    # a stable fingerprint so the coordinator can attribute stragglers
    peer: Optional[str] = None


class MetricSchema(BaseModel):
    """Shape of the full ``{prefix}_metrics`` dictionary value: one
    LocalMetrics per signed peer subkey (metrics_utils.py:17-18)."""

    metrics: Dict[str, LocalMetrics]


def make_validators(
    prefix: str, private_key: Optional[RSAPrivateKey] = None
) -> Tuple[List[RecordValidatorBase], bytes]:
    """[schema, signature] validator chain + this peer's public-key subkey
    (metrics_utils.py:21-24). The checkpoint-catalog schema rides the same
    chain: a malformed shard announcement is rejected at the storing node,
    and announcements published under a peer's owner-tag subkey are
    signature-bound to that peer (dedloc_tpu/checkpointing/catalog.py)."""
    from dedloc_tpu.averaging.planwire import PlanRecord
    from dedloc_tpu.checkpointing.catalog import CheckpointAnnouncement
    from dedloc_tpu.serving.records import ExpertRecord
    from dedloc_tpu.telemetry.ledger import ContributionClaim, RoundReceipt

    signature = RSASignatureValidator(private_key)
    schema = SchemaValidator(
        {
            "metrics": LocalMetrics,
            "checkpoint_catalog": CheckpointAnnouncement,
            # live re-planning records (averaging/planwire.py): a malformed
            # or out-of-range topology plan is rejected at the storing
            # node, not discovered mid-round by every adopting peer
            "topology_plan": PlanRecord,
            # contribution accounting (telemetry/ledger.py): claims and
            # round receipts are schema-checked at every storing node, so
            # the coordinator's fold never sees a structurally bad record
            "contribution_ledger": ContributionClaim,
            "round_receipts": RoundReceipt,
            # expert serving discovery (serving/records.py): a malformed
            # or identity-mismatched expert announcement is rejected at
            # the storing node, not discovered by a routing gateway
            "experts": ExpertRecord,
        },
        prefix=prefix,
    )
    return [schema, signature], signature.local_public_key


def publish_metrics(
    dht: DHT,
    prefix: str,
    subkey: bytes,
    metrics: LocalMetrics,
    expiration: float = 600.0,
) -> None:
    """Store this peer's metrics (statistics_expiration default matches
    albert/arguments.py:82-84)."""
    dht.store(
        f"{prefix}_metrics",
        metrics.model_dump(),
        get_dht_time() + expiration,
        subkey=subkey,
        return_future=True,
    )


# peers whose malformed metrics records were already reported: the drop is
# logged at WARNING once per peer (a wedged peer republishes every few
# seconds — repeating the warning each aggregation tick would bury the log),
# counted through the telemetry registry every time
_malformed_warned: set = set()


def fetch_metrics(dht: DHT, prefix: str) -> List[LocalMetrics]:
    """All currently-live peer metrics (coordinator view,
    run_first_peer.py:177-187). Malformed records are dropped, counted
    (``metrics.malformed_records``) and reported once per peer — a peer
    publishing garbage must be visible, not silently invisible."""
    entry = dht.get(f"{prefix}_metrics", latest=True)
    out: List[LocalMetrics] = []
    if entry is None or not hasattr(entry.value, "items"):
        return out
    import hashlib

    for subkey, v in entry.value.items():
        raw = subkey if isinstance(subkey, bytes) else str(subkey).encode()
        peer = hashlib.sha1(raw).hexdigest()[:12]
        try:
            payload = v.value
            if isinstance(payload, (bytes, bytearray)):
                payload = unpack_obj(payload)
            record = LocalMetrics.model_validate(payload)
            out.append(record.model_copy(update={"peer": peer}))
        except Exception as e:  # noqa: BLE001 — skip malformed peer records
            telemetry.inc("metrics.malformed_records")
            if peer not in _malformed_warned:
                if len(_malformed_warned) >= 4096:
                    # bound the memory on a churning fleet; clearing also
                    # re-arms the warning for a peer that regressed long
                    # after it was first reported
                    _malformed_warned.clear()
                _malformed_warned.add(peer)
                logger.warning(
                    f"dropping malformed metrics record from peer {peer}: "
                    f"{e!r} (reported once; further drops are only counted)"
                )
            continue
    return out


def aggregate_metrics(records: List[LocalMetrics]) -> Optional[dict]:
    """Coordinator aggregation (run_first_peer.py:188-200): alive peers,
    summed throughput/samples, loss averaged over mini-steps of the CURRENT
    global step."""
    if not records:
        return None
    current_step = max(m.step for m in records)
    current = [m for m in records if m.step == current_step]
    sum_mini = sum(m.mini_steps for m in current)
    sum_loss = sum(m.loss for m in current)
    agg = {
        "step": current_step,
        "alive_peers": len(records),
        "samples_accumulated": sum(m.samples_accumulated for m in current),
        "samples_per_second": sum(m.samples_per_second for m in records),
        "loss": (sum_loss / sum_mini) if sum_mini else 0.0,
        "mini_steps": sum_mini,
    }
    telemetry = [
        {
            "peer": m.peer,
            "samples_per_second": m.samples_per_second,
            "step_time_ms": m.step_time_ms,
            "data_wait_ms": m.data_wait_ms,
            "allreduce_ms": m.allreduce_ms,
            "hbm_bytes": m.hbm_bytes,
        }
        for m in current
        if m.step_time_ms is not None
    ]
    if telemetry:
        agg["peer_telemetry"] = telemetry
    return agg
