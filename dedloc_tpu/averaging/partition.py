"""Tensor flattening and bandwidth-weighted partitioning for group all-reduce.

Capability parity with hivemind's load-balanced butterfly partitioning
(``throughput=bandwidth`` at albert/run_trainer.py:258, SURVEY.md §2.6):
peers with more bandwidth reduce proportionally larger chunks, so the round
finishes in min-max-optimal time. Client-mode/zero-bandwidth peers get zero-
size parts — they contribute data but never host a reduction.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def flatten_tree(tree: Dict[str, np.ndarray]) -> Tuple[np.ndarray, List[Tuple[str, Tuple[int, ...], np.dtype]]]:
    """Flatten {name: array} into one fp32 vector + layout spec (sorted by name
    so every peer produces the identical layout)."""
    spec = []
    chunks = []
    for name in sorted(tree):
        arr = np.asarray(tree[name])
        spec.append((name, arr.shape, arr.dtype))
        chunks.append(arr.astype(np.float32).ravel())
    flat = np.concatenate(chunks) if chunks else np.zeros((0,), np.float32)
    return flat, spec


def unflatten_tree(
    flat: np.ndarray, spec: Sequence[Tuple[str, Tuple[int, ...], np.dtype]]
) -> Dict[str, np.ndarray]:
    out = {}
    offset = 0
    for name, shape, dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        out[name] = flat[offset : offset + size].reshape(shape).astype(dtype)
        offset += size
    assert offset == flat.size, "layout spec does not match vector length"
    return out


def partition_weighted(
    total_size: int,
    bandwidths: Sequence[float],
    can_host: Optional[Sequence[bool]] = None,
) -> List[Tuple[int, int]]:
    """Split [0, total_size) into len(bandwidths) contiguous spans with sizes
    proportional to bandwidth (largest-remainder rounding; exact cover).

    ``can_host[i] == False`` forces span i empty regardless of bandwidth —
    used for client-mode members that cannot accept inbound connections. The
    all-zero-bandwidth fallback distributes only among hosting-capable
    members for the same reason."""
    n = len(bandwidths)
    assert n > 0
    hostable = (
        np.ones(n, dtype=bool)
        if can_host is None
        else np.asarray(list(can_host), dtype=bool)
    )
    assert hostable.any(), "at least one member must be able to host"
    bw = np.asarray(bandwidths, dtype=np.float64)
    bw = np.where(np.isfinite(bw) & (bw > 0) & hostable, bw, 0.0)
    if bw.sum() <= 0:
        bw = hostable.astype(np.float64)
    ideal = bw / bw.sum() * total_size
    sizes = np.floor(ideal).astype(np.int64)
    remainder = int(total_size - sizes.sum())
    # distribute leftover to the largest fractional parts
    order = np.argsort(-(ideal - sizes))
    for i in range(remainder):
        sizes[order[i % n]] += 1
    spans = []
    offset = 0
    for s in sizes:
        spans.append((offset, offset + int(s)))
        offset += int(s)
    assert offset == total_size
    return spans
