"""Tensor flattening and bandwidth-weighted partitioning for group all-reduce.

Capability parity with hivemind's load-balanced butterfly partitioning
(``throughput=bandwidth`` at albert/run_trainer.py:258, SURVEY.md §2.6):
peers with more bandwidth reduce proportionally larger chunks, so the round
finishes in min-max-optimal time. Client-mode/zero-bandwidth peers get zero-
size parts — they contribute data but never host a reduction.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class TreeLayout:
    """Precomputed flat layout for a stable {name: array} schema.

    The averaging hot path flattens the identical tree schema every round;
    re-deriving the spec and allocating ``astype`` + ``concatenate``
    intermediates per round costs one full extra copy of the gradient
    vector. A TreeLayout is built once from the first round's tree and then
    ``flatten_into`` writes each tensor straight into ONE preallocated flat
    buffer (the dtype cast happens during the strided copy, no temporary).
    """

    __slots__ = ("spec", "offsets", "total_size", "_buffer")

    def __init__(self, spec: Sequence[Tuple[str, Tuple[int, ...], np.dtype]]):
        self.spec = list(spec)
        self.offsets: List[int] = []
        offset = 0
        for _name, shape, _dtype in self.spec:
            self.offsets.append(offset)
            offset += int(np.prod(shape)) if shape else 1
        self.total_size = offset
        self._buffer: Optional[np.ndarray] = None

    @classmethod
    def for_tree(cls, tree: Dict[str, np.ndarray]) -> "TreeLayout":
        spec = []
        for name in sorted(tree):
            arr = np.asarray(tree[name])
            spec.append((name, arr.shape, arr.dtype))
        return cls(spec)

    def matches(self, tree: Dict[str, np.ndarray]) -> bool:
        if len(tree) != len(self.spec):
            return False
        for name, shape, dtype in self.spec:
            arr = tree.get(name)
            if arr is None:
                return False
            arr = np.asarray(arr)
            if arr.shape != shape or arr.dtype != dtype:
                return False
        return True

    def flatten_into(
        self, tree: Dict[str, np.ndarray], out: Optional[np.ndarray] = None
    ) -> np.ndarray:
        """Write ``tree`` into a flat fp32 vector. ``out=None`` reuses (and
        lazily allocates) the layout's own buffer — callers that hold the
        layout across rounds get a zero-allocation flatten. The returned
        vector is only valid until the next ``flatten_into`` on the same
        buffer."""
        if out is None:
            if self._buffer is None:
                self._buffer = np.empty((self.total_size,), np.float32)
            out = self._buffer
        assert out.size == self.total_size, "buffer does not match layout"
        for (name, shape, _dtype), offset in zip(self.spec, self.offsets):
            arr = np.asarray(tree[name])
            size = int(np.prod(shape)) if shape else 1
            # the cast (if any) happens inside the copy — no astype temp
            np.copyto(
                out[offset : offset + size],
                arr.reshape(-1),
                casting="unsafe",
            )
        return out

    def unflatten(self, flat: np.ndarray) -> Dict[str, np.ndarray]:
        return unflatten_tree(flat, self.spec)

    def tree_view(self, flat: np.ndarray) -> "FlatTree":
        """A ``FlatTree`` over ``flat``: the named-dict view of the buffer
        that ALSO carries the buffer itself, so flat-aware consumers (the
        averager's wire path, the fused flat apply) skip the re-flatten."""
        assert flat.size == self.total_size, "buffer does not match layout"
        return FlatTree(self.unflatten(flat), flat=flat, spec=self.spec)


class FlatTree(dict):
    """A {name: array} gradient tree whose values are VIEWS of one flat
    fp32 buffer in TreeLayout (sorted-name) order.

    Behaves exactly like the plain dict the averaging stack has always
    consumed — ``schema_fingerprint``, serialization, and stubbed tests
    all see a normal mapping — but carries ``.flat`` (the backing buffer)
    and ``.spec`` so flat-native consumers avoid re-flattening what is
    already flat. The buffer may be reused by its producer (double-buffered
    device fetches): treat it as valid only until the producing pipeline's
    next-but-one fetch, the same lifetime contract as
    ``TreeLayout.flatten_into``.
    """

    def __init__(self, mapping, flat: np.ndarray, spec):
        super().__init__(mapping)
        self.flat = flat
        self.spec = list(spec)


def flatten_tree(tree: Dict[str, np.ndarray]) -> Tuple[np.ndarray, List[Tuple[str, Tuple[int, ...], np.dtype]]]:
    """Flatten {name: array} into one fp32 vector + layout spec (sorted by name
    so every peer produces the identical layout). One-shot convenience over
    ``TreeLayout`` — round-loop callers should hold a TreeLayout instead and
    reuse its buffer."""
    layout = TreeLayout.for_tree(tree)
    return layout.flatten_into(tree, np.empty((layout.total_size,), np.float32)), layout.spec


def unflatten_tree(
    flat: np.ndarray, spec: Sequence[Tuple[str, Tuple[int, ...], np.dtype]]
) -> Dict[str, np.ndarray]:
    """Inverse of ``flatten_tree``. When a tensor's target dtype is already
    the vector's dtype the returned array is a reshaped VIEW of ``flat``
    (the old unconditional ``astype`` copied every fp32 tensor twice per
    round); callers that mutate the result in place must copy first."""
    out = {}
    offset = 0
    for name, shape, dtype in spec:
        size = int(np.prod(shape)) if shape else 1
        chunk = flat[offset : offset + size].reshape(shape)
        out[name] = chunk if chunk.dtype == dtype else chunk.astype(dtype)
        offset += size
    assert offset == flat.size, "layout spec does not match vector length"
    return out


def partition_weighted(
    total_size: int,
    bandwidths: Sequence[float],
    can_host: Optional[Sequence[bool]] = None,
) -> List[Tuple[int, int]]:
    """Split [0, total_size) into len(bandwidths) contiguous spans with sizes
    proportional to bandwidth (largest-remainder rounding; exact cover).

    ``can_host[i] == False`` forces span i empty regardless of bandwidth —
    used for client-mode members that cannot accept inbound connections. The
    all-zero-bandwidth fallback distributes only among hosting-capable
    members for the same reason."""
    n = len(bandwidths)
    assert n > 0
    hostable = (
        np.ones(n, dtype=bool)
        if can_host is None
        else np.asarray(list(can_host), dtype=bool)
    )
    assert hostable.any(), "at least one member must be able to host"
    bw = np.asarray(bandwidths, dtype=np.float64)
    bw = np.where(np.isfinite(bw) & (bw > 0) & hostable, bw, 0.0)
    if bw.sum() <= 0:
        bw = hostable.astype(np.float64)
    ideal = bw / bw.sum() * total_size
    sizes = np.floor(ideal).astype(np.int64)
    remainder = int(total_size - sizes.sum())
    # distribute leftover to the largest fractional parts
    order = np.argsort(-(ideal - sizes))
    for i in range(remainder):
        sizes[order[i % n]] += 1
    spans = []
    offset = 0
    for s in sizes:
        spans.append((offset, offset + int(s)))
        offset += int(s)
    assert offset == total_size
    return spans
