"""Epoch-versioned topology plan records on the DHT (live re-planning wire).

The coordinator's closed adaptation loop (roles/coordinator.py) derives a
``TopologyPlan`` from its live link fold and publishes it here — one
dictionary record per collaboration at ``{prefix}_topology_plan``, one
subkey per publisher (the same signed-record machinery as the metrics bus
and the checkpoint catalog: when the subkey is the coordinator's RSA owner
tag the record is signature-bound to it; the ``PlanRecord`` schema below is
validated at every storing node either way, so a malformed or out-of-range
plan is rejected at the DHT boundary, not discovered mid-round).

Peers poll the record between rounds (``DecentralizedAverager.step`` →
``maybe_refresh_plan``) and adopt the highest-epoch valid plan. Adoption
needs no barrier and no handshake: matchmaking scopes embed the plan epoch
(``TopologyPlan.clique_scope``/``wan_scope``/``gossip_scope``), so peers on
epoch k and k+1 form disjoint groups during rollout and converge as fetches
land.

Failure ladder (the robustness contract this module is FOR):

- a transient DHT failure on publish or fetch costs one bounded
  exponential backoff (``plan_sync.retries`` counter + ``plan_sync.retry``
  event per attempt — same retry idiom as state sync), never a crash;
- a fetch that exhausts its retries, or a record that fails the schema,
  returns ``(None, reason)`` — the peer KEEPS its current plan;
- only after ``max_plan_fetch_failures`` consecutive fetch errors does the
  averager degrade to flat (averager.py names the reason in its
  ``avg.topology.fallback`` event) — a dead coordinator demotes the swarm
  to today's flat butterfly, it never strands it.

Fault point ``topology.plan_record`` (testing/faults.py) fires on every
publish/fetch attempt with ``op="publish"|"fetch"`` so tests script record
loss deterministically.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from pydantic import BaseModel, StrictInt, model_validator

from dedloc_tpu.averaging.topology import TopologyPlan
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.testing import faults
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

PLAN_MODES = ("flat", "hierarchical", "gossip")

# actuation tuning keys a plan record may carry (the guard-railed retune's
# distribution channel; roles map them onto averager knobs). Unknown keys
# in a received record are ignored, so new knobs roll out coordinator-first.
TUNING_KEYS = ("chunk_size", "overlap")

# retry budget for one publish/fetch: attempt, then `PLAN_SYNC_RETRIES`
# retries at backoff * 2**(attempt-1) seconds — bounded, like state sync
PLAN_SYNC_RETRIES = 2
PLAN_SYNC_BACKOFF = 0.5

# a peer keeps its current plan through this many CONSECUTIVE failed
# fetches before degrading to flat (averager.py applies this)
MAX_PLAN_FETCH_FAILURES = 3

# plan records outlive several publish intervals so a briefly-partitioned
# peer still finds the current plan when it reconnects
PLAN_RECORD_EXPIRATION = 600.0


def plan_key(prefix: str) -> str:
    return f"{prefix}_topology_plan"


class PlanRecord(BaseModel):
    """Schema for one publisher's plan subkey (validated by the DHT's
    SchemaValidator chain, like the checkpoint catalog)."""

    epoch: StrictInt
    plan: Dict  # TopologyPlan.to_dict() payload
    issued: float  # dht time the coordinator derived this plan
    tuning: Optional[Dict] = None  # guard-railed actuation deltas

    @model_validator(mode="after")
    def _check(self) -> "PlanRecord":
        if self.epoch < 0:
            raise ValueError(f"negative epoch {self.epoch}")
        mode = self.plan.get("mode")
        if mode not in PLAN_MODES:
            raise ValueError(f"unknown plan mode {mode!r}")
        # the plan payload must round-trip the shared parser — a record a
        # storing node accepts but a peer cannot parse would strand that
        # peer mid-rollout
        parsed = TopologyPlan.from_dict(self.plan)
        if int(parsed.epoch) != int(self.epoch):
            raise ValueError(
                f"plan epoch {parsed.epoch} != record epoch {self.epoch}"
            )
        if mode == "hierarchical" and not parsed.cliques:
            raise ValueError("hierarchical plan with no cliques")
        if mode == "gossip" and len(parsed.peers) < 2:
            raise ValueError("gossip plan with fewer than 2 roster peers")
        if self.tuning is not None:
            for k, v in self.tuning.items():
                if not isinstance(k, str) or not isinstance(
                    v, (int, float, bool)
                ):
                    raise ValueError(f"non-scalar tuning entry {k!r}={v!r}")
        return self

    def topology_plan(self) -> TopologyPlan:
        return TopologyPlan.from_dict(self.plan)


def _backoff_sleep(attempt: int, backoff: float, op: str) -> None:
    delay = backoff * (2 ** (attempt - 1))
    telemetry.inc("plan_sync.retries")
    telemetry.event("plan_sync.retry", op=op, attempt=attempt,
                    backoff_s=delay)
    # runtime-only retry pacing: the simulator's closed loop drives the
    # control logic directly and never enters this module
    time.sleep(delay)


def publish_plan(
    dht,
    prefix: str,
    record: PlanRecord,
    subkey: bytes = b"coordinator",
    expiration: float = PLAN_RECORD_EXPIRATION,
    retries: int = PLAN_SYNC_RETRIES,
    backoff: float = PLAN_SYNC_BACKOFF,
) -> bool:
    """Store the coordinator's plan record, retrying transient DHT failures
    with bounded exponential backoff. Returns whether a store succeeded —
    False means every attempt failed and the swarm stays on its previous
    record (which is why records outlive several publish intervals)."""
    for attempt in range(retries + 1):
        if attempt:
            _backoff_sleep(attempt, backoff, "publish")
        try:
            if faults._active is not None:
                fault = faults.fire(
                    "topology.plan_record", op="publish",
                    epoch=int(record.epoch),
                )
                if fault is not None:
                    if fault.action == "drop":
                        # the record is lost in flight: this attempt
                        # "succeeds" locally but stores nothing
                        continue
                    raise OSError("fault injected: plan publish failed")
            ok = dht.store(
                plan_key(prefix),
                record.model_dump(),
                get_dht_time() + expiration,
                subkey=subkey,
            )
            if ok:
                return True
        except Exception as e:  # noqa: BLE001 — a DHT blip is retried
            logger.warning(
                f"plan publish attempt {attempt + 1} failed: {e!r}"
            )
    return False


def parse_plan_entries(entry_items) -> Tuple[Optional[PlanRecord], str]:
    """THE one parsing path for plan records: validate every subkey entry,
    keep the highest epoch, name why nothing was adoptable otherwise.
    ``entry_items`` is an iterable of (subkey, unpacked record dict)."""
    best: Optional[PlanRecord] = None
    reasons = []
    for sk, value in entry_items:
        try:
            rec = PlanRecord.model_validate(value)
        except Exception as e:  # noqa: BLE001 — malformed record named
            reasons.append(f"unparseable plan record: {e!r}")
            logger.debug(f"dropping malformed plan record {sk!r}: {e!r}")
            continue
        if best is None or rec.epoch > best.epoch:
            best = rec
    if best is not None:
        return best, ""
    return None, (reasons[-1] if reasons else "no plan record published")


def fetch_plan(
    dht,
    prefix: str,
    retries: int = PLAN_SYNC_RETRIES,
    backoff: float = PLAN_SYNC_BACKOFF,
) -> Tuple[Optional[PlanRecord], str]:
    """Fetch the newest valid plan record, retrying transient DHT failures
    with bounded exponential backoff. Returns ``(record, "")`` or
    ``(None, reason)`` — the caller decides whether the reason means "keep
    the current plan" (transient) or "degrade to flat" (repeated)."""
    reason = "no plan record published"
    for attempt in range(retries + 1):
        if attempt:
            _backoff_sleep(attempt, backoff, "fetch")
        try:
            if faults._active is not None:
                fault = faults.fire("topology.plan_record", op="fetch")
                if fault is not None:
                    if fault.action == "drop":
                        reason = "plan record lost (fault injected)"
                        continue
                    raise OSError("fault injected: plan fetch failed")
            entry = dht.get(plan_key(prefix), latest=True)
        except Exception as e:  # noqa: BLE001 — a DHT blip is retried
            reason = f"plan fetch failed: {e!r}"
            logger.warning(
                f"plan fetch attempt {attempt + 1} failed: {e!r}"
            )
            continue
        if entry is None or not isinstance(entry.value, dict):
            # an empty record is definitive, not a transient failure: the
            # coordinator has simply not published (or it expired)
            return None, "no plan record published"
        record, parse_reason = parse_plan_entries(
            (sk, v.value) for sk, v in entry.value.items()
        )
        if record is not None:
            return record, ""
        reason = parse_reason
    return None, reason
