"""Device-resident flat gradient pipeline: flatten, quantize and stream the
mean-grad tree OFF the accelerator without blocking the dispatch stream.

The legacy boundary seam (``collaborative/optimizer.py``) crossed the
jit<->host boundary one LEAF at a time: ``jax.device_get`` per gradient
tensor (O(leaves) transfers at full fp32 width), then a host-side
``TreeLayout.flatten_into`` pass, then — under a lossy wire format — a host
encode (fp32 -> fp16/uint8) of bytes that had just crossed PCIe at 4 bytes
per element. This module moves all of that onto the device:

- **flatten**: one jitted program concatenates the tree into ONE flat fp32
  buffer in the same sorted-name ``TreeLayout`` order as the host flatten —
  bit-identical by construction (same per-element ``x / n`` mean and
  ``x * scale`` clip, same ordering; locked by the parity suite in
  ``tests/test_device_flat.py``);
- **mean + contribution clip**: the ``grad_acc / n`` division and the
  contrib-clip global-norm reduce ride the same fused program — ONE
  ``vdot`` over the flat buffer instead of a Python-level sum of per-leaf
  reductions;
- **error feedback**: the quantization residual (DGC-style, see
  ``collaborative/error_feedback.py`` for the lineage and the commit
  discipline this class mirrors) lives on device and is folded into the
  contribution inside the same program;
- **quantize**: under ``float16``/``uint8`` wire formats the compressed
  representation is produced ON DEVICE, so the PCIe transfer carries 2 or
  16 bits per element instead of 32 — the host codec becomes the
  decode-only leg (fp16 widens during one ``np.copyto``; uint8 dequantizes
  per block with its own affine grid, matching ``native.quantize_uint8``
  semantics per block);
- **streaming**: the program returns the buffer pre-split into fixed-size
  chunks; ``copy_to_host_async`` is issued on every chunk at launch, so the
  transfer overlaps whatever the caller does next (the next micro-batches'
  accumulation under overlap averaging, matchmaking otherwise) and
  ``FlatFetch.result()`` only ever pays the NOT-yet-arrived remainder —
  the ``d2h_stream`` step phase / ``opt.d2h_stream`` telemetry event
  record how much of the transfer was actually exposed.

Dtype contract: only floating-point leaves are accepted (fp32/bf16/fp16 —
everything the fp32 flat layout represents exactly). Integer or boolean
leaves are REFUSED at build time with ``ValueError`` — averaging them is
meaningless and the host path would have silently cast; same stance as the
checkpoint manifest's fp32-roundtrip refusal.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dedloc_tpu.averaging.partition import FlatTree, TreeLayout
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry.registry import monotonic_clock
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# fp32 elements per D2H chunk (4 MB): big enough that per-chunk dispatch
# overhead vanishes, small enough that the first chunks land while the rest
# are still in flight. Also the uint8 quantization BLOCK: each chunk gets
# its own affine (lo, scale) grid, so a cold embedding row cannot flatten
# the grid of the whole vector.
DEFAULT_D2H_CHUNK = 1 << 20


def named_device_leaves(tree) -> List[Tuple[str, Any]]:
    """(name, leaf) pairs with the SAME deterministic naming as the
    optimizer's host-side ``_tree_to_named`` (jax keystr paths), so the
    device pipeline's sorted spec matches the host TreeLayout exactly."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for i, (path, leaf) in enumerate(flat):
        name = jax.tree_util.keystr(path) or f"leaf{i}"
        out.append((name, leaf))
    return out


def _chunk_bounds(total: int, chunk: int) -> List[Tuple[int, int]]:
    bounds = []
    offset = 0
    while offset < total:
        bounds.append((offset, min(offset + chunk, total)))
        offset = bounds[-1][1]
    return bounds


# module-level program cache: jitted prepare fns keyed by their static
# signature, so pipeline instances over identical schemas (tests build many
# optimizers over the same tiny trees) share one compiled program
_PREPARE_CACHE: Dict[Tuple, Callable] = {}


def _build_prepare(order, total, chunk, compression, use_ef, use_clip):
    """Compile (with caching) the fused flatten(+mean+clip+EF+quantize+
    split) program for one (spec, options) signature."""
    key = (tuple(order), total, chunk, compression, use_ef, use_clip)
    cached = _PREPARE_CACHE.get(key)
    if cached is not None:
        return cached
    import jax
    import jax.numpy as jnp

    bounds = _chunk_bounds(total, chunk)

    def prepare(leaves, n, cap, residual):
        by_spec = [None] * len(leaves)
        for leaf, pos in zip(leaves, order):
            by_spec[pos] = leaf.astype(jnp.float32).reshape(-1)
        flat = (
            jnp.concatenate(by_spec) if by_spec
            else jnp.zeros((0,), jnp.float32)
        )
        # the grad_acc / n mean, fused — a DIVISION, not a reciprocal
        # multiply, so the result is bit-identical to the host path's
        # per-leaf ``g / n`` (x/3 != x*(1/3) in fp32)
        flat = flat / n
        if use_clip:
            # contrib clip: ONE global-norm reduce on the flat buffer
            # (legacy: a Python-level sum of per-leaf vdots)
            gnorm = jnp.sqrt(jnp.vdot(flat, flat).real)
            flat = flat * jnp.minimum(1.0, cap / (gnorm + 1e-12))
        contrib = flat + residual if use_ef else flat

        if compression == "none":
            wire = tuple(contrib[lo:hi] for lo, hi in bounds)
            return wire, (), contrib if use_ef else None
        if compression == "float16":
            q = contrib.astype(jnp.float16)
            wire = tuple(q[lo:hi] for lo, hi in bounds)
            if not use_ef:
                return wire, (), None
            return wire, (), contrib - q.astype(jnp.float32)
        if compression == "uint8":
            n_blocks = len(bounds)
            pad = n_blocks * chunk - total
            grid = jnp.pad(contrib, (0, pad)).reshape(n_blocks, chunk)
            valid = (
                jnp.arange(n_blocks * chunk).reshape(n_blocks, chunk) < total
            )
            lo = jnp.min(jnp.where(valid, grid, jnp.inf), axis=1)
            hi = jnp.max(jnp.where(valid, grid, -jnp.inf), axis=1)
            # native.quantize_uint8 per block: scale (hi-lo)/255, 0 -> 1.0
            scale = (hi - lo) / 255.0
            scale = jnp.where(scale == 0.0, 1.0, scale)
            q = jnp.clip(
                jnp.rint((grid - lo[:, None]) / scale[:, None]), 0, 255
            ).astype(jnp.uint8)
            wire = tuple(
                q[i, : b_hi - b_lo] for i, (b_lo, b_hi) in enumerate(bounds)
            )
            if not use_ef:
                return wire, (lo, scale), None
            dq = q.astype(jnp.float32) * scale[:, None] + lo[:, None]
            new_residual = contrib - dq.reshape(-1)[:total]
            return wire, (lo, scale), new_residual
        raise ValueError(f"unknown compression {compression!r}")

    fn = jax.jit(prepare)
    _PREPARE_CACHE[key] = fn
    return fn


class FlatFetch:
    """One in-flight device->host transfer of a flat contribution.

    ``result()`` blocks until every chunk has landed, decodes into the
    pipeline's host buffer and returns a ``FlatTree`` over it; it is
    idempotent and thread-safe (the averager resolves it on an executor
    thread, overlapped with matchmaking). ``exposed_wait_s`` is how long
    the FIRST ``result()`` call actually blocked — the portion of the
    transfer nothing else hid.
    """

    def __init__(
        self,
        pipeline: "DeviceFlatPipeline",
        wire_chunks,
        quant_meta,
        new_residual,
        host_buffer: np.ndarray,
    ) -> None:
        self.pipeline = pipeline
        self.spec = pipeline.spec
        self._wire = wire_chunks
        self._meta = quant_meta
        self._new_residual = new_residual
        self._buffer = host_buffer
        self._lock = threading.Lock()
        self._result: Optional[FlatTree] = None
        self.launched_at = monotonic_clock()
        self.exposed_wait_s = 0.0
        self.wire_bytes = sum(int(c.nbytes) for c in wire_chunks) + sum(
            int(m.nbytes) for m in quant_meta
        )

    def result(self) -> FlatTree:
        with self._lock:
            if self._result is not None:
                return self._result
            t0 = monotonic_clock()
            buf = self._buffer
            pipeline = self.pipeline
            if pipeline.compression == "uint8":
                _lo, scale = (np.asarray(m) for m in self._meta)
                for i, (lo_i, hi_i) in enumerate(pipeline.bounds):
                    out = buf[lo_i:hi_i]
                    np.copyto(out, np.asarray(self._wire[i]),
                              casting="unsafe")
                    out *= np.float32(scale[i])
                    out += np.float32(_lo[i])
            else:
                # fp32 passthrough, or the fp16 decode-only leg: the widen
                # happens inside one strided copy into the host buffer
                for (lo_i, hi_i), chunk in zip(pipeline.bounds, self._wire):
                    np.copyto(buf[lo_i:hi_i], np.asarray(chunk),
                              casting="unsafe")
            self.exposed_wait_s = max(0.0, monotonic_clock() - t0)
            self._wire = ()  # release device references
            self._meta = ()
            self._result = pipeline.layout.tree_view(buf)
            pipeline._record_fetch(self)
            return self._result


class DeviceFlatPipeline:
    """Jitted companion to ``TreeLayout`` for one stable gradient schema.

    Built lazily from the first boundary's mean-grad tree; ``fetch()``
    launches the fused device program plus async host copies and returns a
    ``FlatFetch``. Host buffers are DOUBLE-buffered: at most two fetches
    may be outstanding (the overlap path holds one across boundaries while
    the sync fallback starts another) — the returned ``FlatTree`` is valid
    until the next-but-one ``fetch``.

    Error feedback mirrors ``collaborative/error_feedback.py`` exactly:
    ``fetch(use_ef=True)`` folds the committed residual into the
    contribution and computes this round's candidate residual on device;
    ``commit(fetch)`` adopts it ONLY when the round landed, ``reset()``
    drops it after a resync. Unlike the host class, a committed residual
    here also covers the D2H quantization leg — the device-quantized
    representation IS what the host (and therefore the wire) sees, so even
    a singleton round that never touched the network has crossed the lossy
    leg and must commit, not reset (the optimizer handles that switch).
    """

    def __init__(
        self,
        spec: Sequence[Tuple[str, Tuple[int, ...], np.dtype]],
        order: Sequence[int],
        compression: str = "none",
        chunk_elems: int = DEFAULT_D2H_CHUNK,
        telemetry_registry=None,
    ) -> None:
        self.spec = list(spec)
        self.order = tuple(order)
        self.layout = TreeLayout(self.spec)
        self.total = self.layout.total_size
        self.compression = compression
        self.chunk_elems = max(1, int(chunk_elems))
        self.bounds = _chunk_bounds(self.total, self.chunk_elems)
        self.telemetry = telemetry_registry
        self._prepare_cache: Dict[Tuple[bool, bool], Callable] = {}
        self._residual = None  # device flat [total], lazily zeros
        self._buffers = [
            np.empty((self.total,), np.float32) for _ in range(2)
        ]
        self._next_buffer = 0
        self.fetches = 0
        self.wire_bytes_total = 0

    # ------------------------------------------------------------- factory

    @classmethod
    def for_tree(
        cls,
        tree,
        compression: str = "none",
        chunk_elems: int = DEFAULT_D2H_CHUNK,
        telemetry_registry=None,
    ) -> "DeviceFlatPipeline":
        """Build from a gradient pytree (device or host leaves). Raises
        ``ValueError`` on non-floating leaves — the refusal contract."""
        named = named_device_leaves(tree)
        for name, leaf in named:
            dtype = np.dtype(getattr(leaf, "dtype", np.asarray(leaf).dtype))
            # kind 'f' covers the IEEE floats; bfloat16 registers as a
            # void-kind extension dtype but widens exactly to fp32
            if dtype.kind != "f" and dtype.name != "bfloat16":
                raise ValueError(
                    f"device flat pipeline refuses non-float leaf "
                    f"{name!r} ({dtype}): the fp32 flat layout cannot "
                    "represent it (checkpoint-path refusal semantics)"
                )
        names = sorted(name for name, _leaf in named)
        index = {n: i for i, n in enumerate(names)}
        spec = [None] * len(named)
        order = []
        for name, leaf in named:
            shape = tuple(getattr(leaf, "shape", np.asarray(leaf).shape))
            spec[index[name]] = (name, shape, np.dtype(np.float32))
            order.append(index[name])
        return cls(
            spec, order, compression=compression, chunk_elems=chunk_elems,
            telemetry_registry=telemetry_registry,
        )

    def matches_tree(self, tree) -> bool:
        named = named_device_leaves(tree)
        if len(named) != len(self.spec):
            return False
        by_name = {
            name: tuple(getattr(leaf, "shape", np.asarray(leaf).shape))
            for name, leaf in named
        }
        return all(
            by_name.get(name) == tuple(shape)
            for name, shape, _dtype in self.spec
        )

    # ------------------------------------------------------------ EF state

    @property
    def ef_enabled(self) -> bool:
        return self.compression != "none"

    def _residual_dev(self):
        import jax.numpy as jnp

        if self._residual is None:
            self._residual = jnp.zeros((self.total,), jnp.float32)
        return self._residual

    def commit(self, fetch: FlatFetch) -> None:
        """Adopt the round's residual — call only when the round landed."""
        if fetch._new_residual is not None:
            self._residual = fetch._new_residual

    def reset_residual(self) -> None:
        """Drop the carried residual (post-resync: it belongs to gradients
        computed on params this peer no longer holds)."""
        self._residual = None

    def residual_norm(self) -> float:
        if self._residual is None:
            return 0.0
        import jax.numpy as jnp

        return float(jnp.sqrt(jnp.vdot(self._residual, self._residual).real))

    # --------------------------------------------------------------- fetch

    def _prepare_fn(self, use_ef: bool, use_clip: bool) -> Callable:
        key = (use_ef, use_clip)
        fn = self._prepare_cache.get(key)
        if fn is None:
            fn = _build_prepare(
                self.order, self.total, self.chunk_elems, self.compression,
                use_ef, use_clip,
            )
            self._prepare_cache[key] = fn
        return fn

    def fetch(
        self,
        tree,
        n: float = 1.0,
        clip_cap: Optional[float] = None,
        use_ef: bool = True,
    ) -> FlatFetch:
        """Launch the fused prepare program + async D2H for ``tree``.

        ``n`` folds the accumulator mean (the micro-batch count);
        ``clip_cap`` enables the contrib clip at that cap; ``use_ef``
        gates the residual fold (the optimizer passes False for
        zero-weight/gated rounds, matching the host path).
        """
        import jax
        import jax.numpy as jnp

        use_ef = bool(use_ef and self.ef_enabled)
        use_clip = clip_cap is not None
        leaves = [leaf for _name, leaf in named_device_leaves(tree)]
        residual = (
            self._residual_dev() if use_ef
            else jnp.zeros((0,), jnp.float32)
        )
        wire, meta, new_residual = self._prepare_fn(use_ef, use_clip)(
            leaves,
            jnp.float32(n),
            jnp.float32(clip_cap if use_clip else 0.0),
            residual,
        )
        for chunk in wire:
            chunk.copy_to_host_async()
        for m in meta:
            m.copy_to_host_async()
        buf = self._buffers[self._next_buffer]
        self._next_buffer = (self._next_buffer + 1) % len(self._buffers)
        return FlatFetch(self, wire, meta, new_residual, buf)

    def _record_fetch(self, fetch: FlatFetch) -> None:
        self.fetches += 1
        self.wire_bytes_total += fetch.wire_bytes
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("opt.d2h_bytes").inc(fetch.wire_bytes)
            tele.counter("opt.d2h_exposed_s").inc(fetch.exposed_wait_s)
            tele.histogram("opt.d2h_wait_s").observe(fetch.exposed_wait_s)
            tele.event(
                "opt.d2h_stream",
                bytes=fetch.wire_bytes,
                exposed_s=fetch.exposed_wait_s,
                chunks=len(self.bounds),
                compression=self.compression,
            )
