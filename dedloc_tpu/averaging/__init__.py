from dedloc_tpu.averaging.partition import partition_weighted, flatten_tree, unflatten_tree
from dedloc_tpu.averaging.allreduce import GroupAllReduce, AllreduceFailed
from dedloc_tpu.averaging.matchmaking import Matchmaking, GroupInfo
from dedloc_tpu.averaging.averager import DecentralizedAverager
