from dedloc_tpu.averaging.partition import (
    TreeLayout,
    flatten_tree,
    partition_weighted,
    unflatten_tree,
)
from dedloc_tpu.averaging.allreduce import GroupAllReduce, AllreduceFailed
from dedloc_tpu.averaging.matchmaking import Matchmaking, GroupInfo
from dedloc_tpu.averaging.topology import (
    CliquePlan,
    TopologyPlan,
    clique_groups,
    plan_from_groups,
    plan_topology,
)
from dedloc_tpu.averaging.averager import DecentralizedAverager
