"""DecentralizedAverager: matchmaking + group all-reduce + state sharing.

The TPU-native counterpart of hivemind.DecentralizedAverager as consumed via
CollaborativeOptimizer (SURVEY.md §2.6). Runs entirely on the DHT facade's
event loop; exposes a synchronous ``step`` for the trainer thread.

In the TPU design the entity calling ``step`` is one pod SLICE (gradients
already psum-reduced over ICI by the jitted step); this class only moves
bytes across slices over DCN/TCP.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import hashlib
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dedloc_tpu.averaging.allreduce import (
    DEFAULT_CHUNK_SIZE,
    AllreduceFailed,
    GroupAllReduce,
)
from dedloc_tpu.averaging.matchmaking import (
    GroupInfo,
    Matchmaking,
    MatchmakingFailed,
)
from dedloc_tpu.averaging.partition import FlatTree, TreeLayout
from dedloc_tpu.averaging.planwire import MAX_PLAN_FETCH_FAILURES, fetch_plan
from dedloc_tpu.averaging.topology import TopologyPlan
from dedloc_tpu.checkpointing import (
    CheckpointAnnouncement,
    CheckpointManifest,
    ShardStore,
    build_manifest,
    catalog_key,
    parse_announcements,
    publish_announcement,
    shard_bytes,
    sharded_restore,
)
from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    deserialize_tree,
    pack_obj,
    serialize_array,
    serialize_tree,
    unpack_obj,
)
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.dht import DHT
from dedloc_tpu.dht.protocol import RPCClient, RPCError, RPCServer
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry.ledger import (
    ContributionClaim,
    parse_round_step,
    publish_claim,
    publish_receipt,
    receipt_from_group,
)
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.testing import faults
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def schema_fingerprint(tree: Dict[str, np.ndarray]) -> bytes:
    """Order-independent hash of (name, shape, dtype) — the join-time
    compatibility handshake: peers whose trees cannot all-reduce together
    are refused by leaders instead of failing a span assert mid-round."""
    h = hashlib.sha256()
    for name in sorted(tree):
        arr = tree[name]
        h.update(name.encode())
        h.update(str(tuple(arr.shape)).encode())
        h.update(str(arr.dtype).encode())
    return h.digest()[:16]


def spec_fingerprint(spec) -> bytes:
    """``schema_fingerprint`` computed from a TreeLayout spec alone — the
    same digest a named-dict view of the buffer would produce, so a peer
    contributing through the device-flat pipeline (``FlatFetch``) can join
    matchmaking BEFORE its device->host transfer has resolved."""
    h = hashlib.sha256()
    for name, shape, dtype in sorted(spec, key=lambda entry: entry[0]):
        h.update(name.encode())
        h.update(str(tuple(shape)).encode())
        h.update(str(np.dtype(dtype)).encode())
    return h.digest()[:16]


class DecentralizedAverager:
    def __init__(
        self,
        dht: DHT,
        prefix: str,
        bandwidth: float = 1000.0,
        client_mode: bool = False,
        auxiliary: bool = False,
        allow_state_sharing: bool = True,
        compression: str | CompressionType = CompressionType.FLOAT16,
        chunk_size: int = DEFAULT_CHUNK_SIZE,  # elements per wire chunk in
        # the pipelined all-reduce; <= 0 restores monolithic spans
        averaging_expiration: float = 5.0,
        averaging_timeout: float = 30.0,
        target_group_size: int = 256,
        listen_host: str = "0.0.0.0",
        listen_port: int = 0,
        advertised_host: Optional[str] = None,
        authorizer=None,  # TokenAuthorizerBase for gated runs (joiner side)
        authority_public_key: Optional[bytes] = None,  # leader-side gate
        relay: Optional[str] = None,  # "host:port[,host2:port2,…]" public
        # peers whose RelayService makes this client-mode peer reachable
        # (circuit relay, p2p/circuit-relay.md); registration is
        # k-redundant and the advertised endpoint fails over when the
        # primary relay dies. Listening peers all serve as relays.
        relay_keepalive_period: float = 5.0,
        # state-sync retry budget: a dead or corrupt provider costs one
        # exponential backoff instead of a failed join (see
        # load_state_from_peers)
        state_sync_retries: int = 2,
        state_sync_backoff: float = 0.5,
        # swarm checkpointing (dedloc_tpu/checkpointing, --checkpoint.*):
        # fp32 elements per content-addressed shard of the shared state.
        # <= 0 (the component default) disables sharded serving, catalog
        # announcements AND the sharded restore path — everything stays on
        # the full blob. The CollaborativeOptimizer / role configs default
        # it ON (DEFAULT_SHARD_SIZE); bare averagers opt in explicitly.
        checkpoint_shard_size: int = 0,
        # concurrent shard downloads during a sharded restore
        checkpoint_fetch_parallelism: int = 4,
        # cap on distinct providers one restore spreads across (0 = all)
        checkpoint_max_providers: int = 0,
        # local shard store for RESUMABLE restores (and as a by-product a
        # durable shard cache); None = in-memory only
        checkpoint_dir: Optional[str] = None,
        # the peer's signed metrics subkey (rsa: owner tag): when given,
        # catalog announcements ride it and are signature-bound to this
        # peer by the existing record-validator chain
        signed_subkey: Optional[bytes] = None,
        # per-peer telemetry scope (telemetry/registry.py): in-process
        # multi-peer tests pass one registry per simulated peer; production
        # (one peer per process) leaves None and the process-global
        # registry — if installed — is used at each instrumented site
        telemetry_registry=None,
        # hierarchical (two-level) averaging plan (averaging/topology.py;
        # --averager.topology_plan): a TopologyPlan, or a path to its JSON.
        # None / mode="flat" keeps today's flat butterfly. Installable
        # later via set_topology_plan (e.g. replanned from live telemetry).
        topology_plan=None,
        # live re-planning (averaging/planwire.py): when True, ``step``
        # polls the coordinator's epoch-versioned plan record between
        # rounds and adopts the newest valid plan — the closed adaptation
        # loop. Defaults OFF for bare averagers; the roles enable it unless
        # the operator pinned a manual --averager.topology_plan (the
        # opt-out, docs/fleet.md). Repeated fetch failures degrade to the
        # held plan and ultimately to flat (MAX_PLAN_FETCH_FAILURES).
        plan_follow: bool = False,
        plan_refresh_period: float = 30.0,  # dht-time seconds between polls
        # contribution-ledger receipts (telemetry/ledger.py): countersign
        # each finalized round's member set + declared weights into this
        # peer's signed RoundReceipt DHT record. ON by default — receipts
        # are what makes any peer's contribution claim checkable; a receipt
        # failure only ever logs, it can never cost a round.
        ledger_receipts: bool = True,
        # dht/transport.py seam for this peer's averaging RPC server and
        # client: None = real TCP (production); the swarm simulator injects
        # its in-process network here
        transport=None,
    ):
        if relay and not client_mode:
            # a listening peer IS a relay; accepting (and dropping) the flag
            # would leave a NAT-ed operator who forgot client_mode with an
            # unreachable advertised address and no signal why
            raise ValueError(
                "relay= is for client-mode peers (set client_mode=True); "
                "listening peers serve as relays themselves"
            )
        self.dht = dht
        self.prefix = prefix
        self.client_mode = client_mode
        self.auxiliary = auxiliary
        self.allow_state_sharing = allow_state_sharing and not client_mode
        self.compression = (
            CompressionType(compression)
            if isinstance(compression, str)
            else compression
        )
        self.chunk_size = int(chunk_size)
        # zero-copy flatten: the tree schema is stable across rounds, so ONE
        # TreeLayout (with its preallocated flat buffer) serves every round;
        # rebuilt only if the schema ever changes
        self._layout: Optional[TreeLayout] = None
        self.averaging_expiration = averaging_expiration
        self.averaging_timeout = averaging_timeout
        self.target_group_size = target_group_size
        self.relay_keepalive_period = relay_keepalive_period
        self.state_sync_retries = int(state_sync_retries)
        self.state_sync_backoff = float(state_sync_backoff)
        self.telemetry = telemetry_registry
        self._listen = (listen_host, listen_port)
        self._advertised_host = advertised_host or "127.0.0.1"
        self._shared_state: Optional[Tuple[Dict[str, np.ndarray], Dict[str, Any]]] = None
        # serialized snapshot cache: (blob, sha256 digest) — the digest rides
        # every state.get reply so downloaders detect truncation/corruption
        self._shared_state_blob: Optional[Tuple[bytes, bytes]] = None
        self._state_lock = threading.Lock()
        self._serialize_task: Optional[asyncio.Task] = None
        # sharded snapshot cache: (manifest, flat fp32 vector) cut from the
        # SAME shared-state snapshot — built lazily (first ckpt RPC or
        # catalog publish), invalidated with the snapshot
        self.checkpoint_shard_size = int(checkpoint_shard_size)
        self.checkpoint_fetch_parallelism = int(checkpoint_fetch_parallelism)
        self.checkpoint_max_providers = int(checkpoint_max_providers)
        self.signed_subkey = signed_subkey
        self._ckpt_store = (
            ShardStore(checkpoint_dir) if checkpoint_dir else None
        )
        self._sharded_state: Optional[Tuple[CheckpointManifest, np.ndarray]] = None
        # (snapshot, message) when the snapshot cannot roundtrip the fp32
        # flat layout — cached so the full-state flatten is not retried
        # (and the warning not repeated) on every publish cadence / ckpt RPC
        self._sharded_state_error: Optional[Tuple[Any, str]] = None
        self._shard_task: Optional[asyncio.Task] = None
        self.server: Optional[RPCServer] = None
        self.endpoint = None
        self.last_group_size: int = 1
        self.last_contributors: int = 1
        # hierarchical averaging state: the installed plan, and the fan-out
        # futures a delegate publishes each round's final result through
        # (clique members pull them via the avg.final RPC)
        self._topology_plan: Optional[TopologyPlan] = None
        self._hier_results: Dict[str, asyncio.Future] = {}
        if topology_plan is not None:
            self.set_topology_plan(topology_plan)
        # live re-planning state: what we last adopted — (epoch, issued)
        # orders records so a same-epoch republish with newer tuning is
        # adopted without a scope reshuffle, and consecutive fetch failures
        # are counted toward the degrade-to-flat threshold
        self.plan_follow = bool(plan_follow)
        self.plan_refresh_period = float(plan_refresh_period)
        self.plan_tuning: Dict[str, Any] = {}
        self._plan_epoch = 0
        self._plan_issued = float("-inf")
        self._plan_fetch_failures = 0
        self._plan_next_refresh = 0.0
        # contribution ledger (telemetry/ledger.py): this peer's cumulative
        # witness table over group-mates' declared weights — refreshed into
        # a signed RoundReceipt DHT record at every round finalization
        self._ledger_witness: Dict[str, Dict[str, float]] = {}
        self.ledger_receipts = bool(ledger_receipts)

        # build server+matchmaking+allreduce on the DHT loop
        def _setup(node):
            async def setup():
                from dedloc_tpu.dht.protocol import RelayService

                self.client = RPCClient(
                    request_timeout=averaging_timeout,
                    telemetry_registry=self.telemetry,
                    transport=transport,
                )
                if not client_mode:
                    self.server = RPCServer(
                        *self._listen, telemetry_registry=self.telemetry,
                        transport=transport,
                    )
                    self.server.register("state.get", self._rpc_state_get)
                    # swarm checkpointing: serve the sharded form of the
                    # same snapshot (full-blob state.get stays the fallback)
                    self.server.register(
                        "ckpt.manifest", self._rpc_ckpt_manifest
                    )
                    self.server.register("ckpt.shard", self._rpc_ckpt_shard)
                    # hierarchical averaging fan-out: clique members pull
                    # the WAN round's final result from their delegate
                    self.server.register("avg.final", self._rpc_hier_final)
                    await self.server.start()
                    self.endpoint = (self._advertised_host, self.server.port)
                    tele_setup = telemetry.resolve(self.telemetry)
                    if tele_setup is not None:
                        # self-identification for the topology views: maps
                        # this peer's label to the endpoint other peers'
                        # link estimates name as their dst
                        tele_setup.event(
                            "peer.endpoint",
                            endpoint=endpoint_key(self.endpoint),
                        )
                    # every public peer doubles as a circuit relay for
                    # private peers (p2p/circuit-relay.md relay_enabled)
                    self.relay_service = RelayService(self.server)
                if authorizer is not None:
                    # gated runs bind peer identity to the token key so
                    # leaders/joiners can verify who signed what (see
                    # matchmaking identity binding)
                    from dedloc_tpu.core.auth import peer_id_from_public_key

                    self.peer_id = peer_id_from_public_key(
                        authorizer.local_public_key
                    )
                elif self.signed_subkey and bytes(
                    self.signed_subkey
                ).startswith(b"rsa:"):
                    # open runs with a record-signing key: derive the peer
                    # id from the SAME key digest gated runs use, so this
                    # peer's signed ledger records bind to its identity
                    # (telemetry/ledger.subkey_owner_id)
                    from dedloc_tpu.core.auth import peer_id_from_public_key
                    from dedloc_tpu.dht.validation import OWNER_PREFIX

                    self.peer_id = peer_id_from_public_key(
                        bytes(self.signed_subkey)[len(OWNER_PREFIX):]
                    )
                else:
                    self.peer_id = node.node_id.to_bytes()
                if client_mode and relay:
                    # circuit relay: park an outbound connection at EVERY
                    # listed public peer (comma-separated "host:port,…" —
                    # the reference's private peers bootstrap off several
                    # public nodes, p2p/NAT-traversal.md:20-23, so one
                    # relay dying must not strand the peer); our RPC
                    # methods (mm.join, allreduce; state.get is withheld —
                    # no state sharing in client mode) become reachable at
                    # the PRIMARY relay's virtual endpoint, and the
                    # keepalive fails the advertisement over to a live
                    # backup when the primary dies.
                    relay_eps = []
                    for spec in str(relay).split(","):
                        spec = spec.strip()
                        if spec:
                            host, _, port = spec.rpartition(":")
                            relay_eps.append((host, int(port)))
                    registry = RPCServer()  # handler registry; never listens
                    self.server = registry
                    self.client.reverse_handlers = registry._handlers
                    self._relay_endpoints = relay_eps
                    # relays we COMPLETED a registration with — failover must
                    # never advertise a relay that merely has a TCP
                    # connection (e.g. a non-relay RPC server would answer
                    # pings yet route nothing)
                    self._registered_relays: set = set()
                    self.endpoint = None
                    for ep in relay_eps:
                        try:
                            vep = await self.client.register_with_relay(
                                ep, self.peer_id
                            )
                            self._registered_relays.add(ep)
                            logger.info(f"registered with relay {ep}")
                            if self.endpoint is None:
                                self.endpoint = vep  # primary = first live
                        except Exception as e:  # noqa: BLE001
                            logger.warning(
                                f"relay {ep} registration failed: {e!r}"
                            )
                    if self.endpoint is None:
                        raise ConnectionError(
                            f"could not register with any relay of "
                            f"{relay_eps}"
                        )

                    async def keep_registered() -> None:
                        # ACTIVE liveness probe per relay: a dropped relay
                        # connection silently unregisters us, and a
                        # half-open one (relay power loss, NAT mapping
                        # expiry with no FIN) never raises EOF — so ping
                        # each relay over its parked connection every
                        # period. The ping shares the ordered byte stream
                        # with multi-MB relayed tensor frames, so a single
                        # slow pong is NOT evidence of death: generous
                        # timeout, an RPC-level error reply counts as alive
                        # (the connection answered), and a connection is
                        # only dropped after two consecutive silent
                        # failures. When the PRIMARY relay is gone, the
                        # advertised endpoint fails over to a live backup —
                        # fresh matchmaking/state records then carry the
                        # new virtual endpoint.
                        from dedloc_tpu.dht.protocol import (
                            parse_relay_endpoint,
                            relay_endpoint,
                        )

                        period = self.relay_keepalive_period
                        ping_failures = {ep: 0 for ep in relay_eps}

                        async def check_relay(ep) -> None:
                            if ep in self.client._conns:
                                try:
                                    await self.client.call(
                                        ep, "relay.ping", {},
                                        timeout=max(10.0, 2 * period),
                                    )
                                    ping_failures[ep] = 0
                                except RPCError:
                                    ping_failures[ep] = 0  # answered
                                except Exception:  # noqa: BLE001
                                    ping_failures[ep] += 1
                                    if ping_failures[ep] >= 2:
                                        self.client._drop(
                                            ep,
                                            ConnectionResetError(
                                                "relay ping timed out twice"
                                            ),
                                        )
                                        self._registered_relays.discard(ep)
                                        ping_failures[ep] = 0
                            if (ep not in self.client._conns
                                    or ep not in self._registered_relays):
                                try:
                                    await self.client.register_with_relay(
                                        ep, self.peer_id
                                    )
                                    self._registered_relays.add(ep)
                                    logger.info(
                                        f"re-registered with relay {ep}"
                                    )
                                except Exception as e:  # noqa: BLE001
                                    self._registered_relays.discard(ep)
                                    logger.debug(
                                        f"relay re-register {ep}: {e!r}"
                                    )

                        while True:
                            await asyncio.sleep(period)
                            # in parallel: one half-open relay must not
                            # stall liveness detection for the others
                            await asyncio.gather(
                                *(check_relay(ep) for ep in relay_eps)
                            )
                            parsed = parse_relay_endpoint(self.endpoint)
                            primary = parsed[0] if parsed else None
                            healthy = [
                                ep for ep in relay_eps
                                if ep in self.client._conns
                                and ep in self._registered_relays
                            ]
                            if primary not in healthy and healthy:
                                ep = healthy[0]
                                self.endpoint = relay_endpoint(
                                    ep, self.peer_id
                                )
                                if hasattr(self, "matchmaking"):
                                    self.matchmaking.endpoint = self.endpoint
                                logger.warning(
                                    f"relay failover: advertising via {ep}"
                                )

                    self._relay_keepalive = asyncio.ensure_future(
                        keep_registered()
                    )
                # NAT traversal (dht/nat.py): calls to relay: endpoints
                # upgrade to direct paths — connection reversal when we are
                # public, hole punch when both sides are private — so the
                # relay carries only handshakes, never tensor bytes
                from dedloc_tpu.dht.nat import NatTraversal

                if self.endpoint is not None and self.server.port is not None:
                    self.nat = NatTraversal(
                        self.client, self.server, self.peer_id,
                        advertised=self.endpoint,
                    )
                elif client_mode and relay:
                    conn = next(
                        (self.client._conns[ep]
                         for ep in self._relay_endpoints
                         if ep in self.client._conns),
                        None,
                    )
                    bind_host = "127.0.0.1"
                    if conn is not None:
                        sockname = conn[1].get_extra_info("sockname")
                        if sockname:
                            bind_host = sockname[0]
                    self.nat = NatTraversal(
                        self.client, self.server, self.peer_id,
                        advertised=None, bind_host=bind_host,
                    )
                else:
                    self.nat = None

                self.allreduce = GroupAllReduce(
                    self.client,
                    self.server,
                    compression=self.compression,
                    timeout=averaging_timeout,
                    straggler_timeout=averaging_expiration,
                    chunk_size=self.chunk_size,
                    telemetry_registry=self.telemetry,
                )
                self.matchmaking = Matchmaking(
                    node,
                    self.client,
                    self.server,
                    prefix,
                    self.peer_id,
                    self.endpoint,
                    bandwidth,
                    target_group_size=target_group_size,
                    averaging_expiration=averaging_expiration,
                    authorizer=authorizer,
                    authority_public_key=authority_public_key,
                    aux=auxiliary,
                    chunk_size=self.chunk_size,
                    telemetry_registry=self.telemetry,
                )

            return setup()

        dht.run_coroutine(_setup)

    # ------------------------------------------------------------ averaging

    def step(
        self,
        tree: Dict[str, np.ndarray],
        weight: float,
        round_id: str,
        return_future: bool = False,
        expected_size: Optional[int] = None,
        window: Optional[float] = None,
    ):
        """Average ``tree`` with whatever group forms for ``round_id``.

        ``tree`` is a {name: array} mapping — or a ``FlatFetch`` from the
        device-flat pipeline (``averaging/device_flat.py``), whose D2H
        transfer is then resolved on an executor thread CONCURRENTLY with
        matchmaking. Successful rounds return a ``FlatTree`` (a dict whose
        values view one flat buffer), so flat-native callers skip the
        re-flatten.

        Returns (averaged_tree | None, group_size); None means the round
        failed and the caller should proceed with its local values
        (reference semantics: a failed group costs one round, nothing else).

        ``weight`` is this peer's averaging weight — normally its accumulated
        sample count. The contribution ramp / trunk-health gate
        (collaborative optimizer) scale it down for freshly-joined or
        diverged peers: a reduced weight mixes proportionally less into the
        group mean, and ``weight == 0.0`` contributes NOTHING while still
        receiving the group's averaged result (a receive-only join; in a
        singleton group a zero-weight round returns None — there is nothing
        to receive).

        ``expected_size``: the collaboration's live peer count, if known —
        lets the leader assemble the moment the group is full instead of
        idling out the straggler window (matchmaking.form_group).

        ``window``: per-round override of ``averaging_expiration`` — the
        collaborative optimizer shortens the leader wait when the partners
        it is waiting on are only NEAR the current step (they may never
        arrive; see CollaborationState.num_peers_near_step).
        """
        if self.plan_follow:
            try:
                self.maybe_refresh_plan()
            except Exception as e:  # noqa: BLE001 — a plan-refresh bug
                # must never cost a training round
                logger.warning(f"plan refresh failed: {e!r}")

        def _run(node):
            return self._step_async(
                tree, weight, round_id, expected_size, window
            )

        fut = self.dht.run_coroutine(_run, return_future=True)
        return fut if return_future else fut.result()

    async def _step_async(
        self, tree: Dict[str, np.ndarray], weight: float, round_id: str,
        expected_size: Optional[int] = None,
        window: Optional[float] = None,
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        tele = telemetry.resolve(self.telemetry)
        if tele is None:  # telemetry off: the bare path, zero overhead
            return await self._step_inner(
                tree, weight, round_id, expected_size, window
            )
        # one span per averaging round: matchmaking + allreduce + weight,
        # the unit the operator asks "why was step N slow" about. The trace
        # id derives from the swarm-unique round_id, so every member's spans
        # (and, via the RPC framing's trace context, every serve span they
        # cause on other peers) stitch into ONE cross-peer trace
        with tele.span(
            "avg.round", trace_seed=round_id, round_id=round_id,
            weight=weight,
        ) as ctx:
            averaged, group_size = await self._step_inner(
                tree, weight, round_id, expected_size, window
            )
            ctx["ok"] = averaged is not None
            ctx["group_size"] = group_size
            return averaged, group_size

    async def _step_inner(
        self, tree, weight: float, round_id: str,
        expected_size: Optional[int] = None,
        window: Optional[float] = None,
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        # the round's declared sample weight rides the member record (and
        # its signed join envelope in gated runs): what group-mates
        # countersign in their contribution-ledger RoundReceipts
        self.matchmaking.declared_weight = max(0.0, float(weight))
        plan = self._topology_plan
        if plan is not None and plan.mode == "hierarchical":
            return await self._step_hier(
                tree, weight, round_id, expected_size, window, plan
            )
        if plan is not None and plan.mode == "gossip":
            return await self._step_gossip(
                tree, weight, round_id, expected_size, window, plan
            )
        return await self._step_flat(
            tree, weight, round_id, expected_size, window
        )

    def _flatten(self, tree) -> np.ndarray:
        """Flat fp32 view of ``tree`` in stable layout order, through the
        reused TreeLayout buffer (valid until the next flatten — the
        all-reduce reads it only within run())."""
        if isinstance(tree, FlatTree):
            # already flat in layout order: skip the host re-flatten pass
            if self._layout is None or self._layout.spec != tree.spec:
                self._layout = TreeLayout(tree.spec)
            return tree.flat
        if self._layout is None or not self._layout.matches(tree):
            self._layout = TreeLayout.for_tree(tree)
        # flatten into the layout's reused buffer: no astype/concatenate
        # temporaries on the hot path
        return self._layout.flatten_into(tree)

    async def _step_flat(
        self, tree, weight: float, round_id: str,
        expected_size: Optional[int] = None,
        window: Optional[float] = None,
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        # device-flat contribution (averaging/device_flat.py FlatFetch):
        # the flat buffer is still streaming off the accelerator — resolve
        # it on an executor thread CONCURRENTLY with matchmaking, so the
        # D2H transfer hides behind group formation instead of preceding it
        from dedloc_tpu.averaging.device_flat import FlatFetch

        fetch = None
        if isinstance(tree, FlatFetch):
            fetch = tree
            tree = None
            loop = asyncio.get_running_loop()
            resolve_task = loop.run_in_executor(None, fetch.result)
        try:
            group = await self.matchmaking.form_group(
                round_id,
                schema=(
                    spec_fingerprint(fetch.spec) if fetch is not None
                    else schema_fingerprint(tree)
                ),
                expected_size=expected_size, window=window,
            )
        except MatchmakingFailed as e:
            logger.debug(f"matchmaking failed for {round_id}: {e}")
            self.last_contributors = 0
            if fetch is not None:
                # settle the in-flight transfer even on failure: the
                # pipeline's double buffer rotates on the NEXT fetch, so an
                # unresolved transfer must not be left dangling
                await resolve_task
            return None, 1
        if fetch is not None:
            try:
                tree = await resolve_task
            except Exception as e:  # noqa: BLE001 — a failed D2H/decode
                # costs one round, never the training process
                logger.warning(f"{round_id}: device-flat fetch failed: {e!r}")
                self.last_contributors = 0
                return None, 1
        self.last_group_size = len(group.members)
        # gradient-bearing member count for the caller's divergence guard:
        # a {trainer, aux} group averages nothing for the trainer
        self.last_contributors = group.contributors
        if len(group.members) == 1:
            return (tree if weight > 0 else None), 1
        flat = self._flatten(tree)
        try:
            # the nonce is fresh per group assembly, so a retried round never
            # collides with _RoundState left over from a failed attempt
            averaged = await self.allreduce.run(
                f"{self.prefix}:{round_id}:{group.nonce}",
                group.my_index,
                flat,
                weight,
                group.endpoints,
                group.bandwidths,
                # chunk geometry must be identical on every member: use the
                # group-negotiated size (min of advertised; 0 = monolithic
                # if any member can't chunk), never the local config alone
                chunk_size=group.chunk_size,
            )
        except AllreduceFailed as e:
            logger.warning(f"allreduce failed for {round_id}: {e}")
            return None, len(group.members)
        self._emit_receipt(group, round_id, "flat")
        # a FlatTree result: the named views every existing consumer reads,
        # plus the flat buffer itself so a flat-native caller (the fused
        # flat apply) device_puts ONE array instead of per-leaf pieces
        return self._layout.tree_view(averaged), len(group.members)

    # -------------------------------------------------- gossip averaging

    async def _step_gossip(
        self, tree, weight: float, round_id: str,
        expected_size: Optional[int],
        window: Optional[float],
        plan: TopologyPlan,
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        """One gossip round (the planner's third interpolation point, for
        very-unreliable swarms): average with a small deterministic
        neighbor group instead of the whole swarm. Every same-plan peer
        derives the identical per-round pairing from the plan roster
        (``TopologyPlan.gossip_groups`` — seeded by epoch + round_id, so
        pairs rotate every round and the swarm mixes over time), then runs
        a plain flat all-reduce inside its pair's scope. A missing partner
        is NOT a failure — the peer keeps its local values and mixes on a
        future pairing (that locality is the point: one flaky peer costs
        its pair a round, never the swarm). Matchmaking/allreduce errors
        fall back to ONE flat round, the same ladder as hierarchical."""
        from dedloc_tpu.averaging.device_flat import FlatFetch

        tele = telemetry.resolve(self.telemetry)
        my_key = endpoint_key(self.endpoint) if self.endpoint else None

        async def fallback(reason: str, fetched_tree):
            if tele is not None:
                tele.counter("avg.topology.fallbacks").inc()
                tele.event(
                    "avg.topology.fallback", round_id=round_id,
                    reason=reason,
                )
            return await self._step_flat(
                fetched_tree, weight, round_id, expected_size, window
            )

        members = plan.gossip_group_of(
            [my_key] if my_key else [], round_id
        )
        if members is None:
            # not in the roster (late joiner since the plan was derived):
            # ride a flat round until the next re-plan includes us
            return await fallback("no identity in gossip roster", tree)

        # device-flat contribution: resolve the D2H transfer concurrently
        # with matchmaking, same as the flat path
        fetch = None
        if isinstance(tree, FlatFetch):
            fetch = tree
            tree = None
            resolve_task = asyncio.get_running_loop().run_in_executor(
                None, fetch.result
            )
        schema = (
            spec_fingerprint(fetch.spec) if fetch is not None
            else schema_fingerprint(tree)
        )

        async def settle() -> bool:
            nonlocal tree
            if fetch is not None and tree is None:
                try:
                    tree = await resolve_task
                except Exception as e:  # noqa: BLE001 — one round lost,
                    # never the training process
                    logger.warning(
                        f"{round_id}: device-flat fetch failed: {e!r}"
                    )
                    return False
            return True

        try:
            group = await self.matchmaking.form_group(
                round_id, schema=schema, expected_size=len(members),
                window=window, scope=plan.gossip_scope(members),
            )
        except MatchmakingFailed as e:
            logger.debug(f"gossip matchmaking failed for {round_id}: {e}")
            if not await settle():
                self.last_contributors = 0
                return None, 1
            return await fallback("gossip matchmaking failed", tree)
        if not await settle():
            self.last_contributors = 0
            return None, 1
        self.last_group_size = len(group.members)
        self.last_contributors = group.contributors
        if len(group.members) == 1:
            # partner absent this round: local values carry forward and mix
            # on a future pairing — by design, not a fallback
            return (tree if weight > 0 else None), 1
        flat = self._flatten(tree)
        try:
            averaged = await self.allreduce.run(
                f"{self.prefix}:{round_id}:{group.nonce}",
                group.my_index, flat, weight,
                group.endpoints, group.bandwidths,
                chunk_size=group.chunk_size,
            )
        except AllreduceFailed as e:
            logger.warning(f"gossip round failed for {round_id}: {e}")
            return await fallback("gossip round failed", tree)
        if tele is not None:
            tele.counter("avg.topology.rounds").inc()
            tele.event(
                "avg.topology.round", round_id=round_id, role="gossip",
                group_size=len(group.members), ok=True,
            )
        self._emit_receipt(group, round_id, "gossip")
        return self._layout.tree_view(averaged), len(group.members)

    # ---------------------------------------------- hierarchical averaging

    def set_topology_plan(self, plan) -> None:
        """Install (or clear, with None) the two-level averaging plan
        (averaging/topology.py). Accepts a ``TopologyPlan`` or a path to
        its JSON serialization. Takes effect on the next ``step``; the
        plan is stamped onto the event trace so operators can see WHICH
        hierarchy a round ran under."""
        if isinstance(plan, str):
            plan = TopologyPlan.load(plan)
        self._topology_plan = plan
        tele = telemetry.resolve(self.telemetry)
        if tele is not None and plan is not None:
            tele.event(
                "avg.topology.plan", mode=plan.mode, reason=plan.reason,
                cliques=len(plan.cliques),
                planned_peers=sum(len(c.members) for c in plan.cliques),
            )

    # ------------------------------------------------- live plan following

    def maybe_refresh_plan(self) -> None:
        """Poll the coordinator's plan record (averaging/planwire.py) and
        adopt the newest valid plan — called from ``step`` between rounds
        when ``plan_follow`` is on, rate-limited to ``plan_refresh_period``
        dht-time seconds. Adoption needs no barrier: the plan epoch is
        embedded in every matchmaking scope, so peers mid-rollout form
        disjoint (still valid) groups. The failure ladder: a transient
        fetch failure keeps the current plan; ``MAX_PLAN_FETCH_FAILURES``
        CONSECUTIVE failures degrade to flat with the reason named on the
        ``avg.topology.fallback`` event — a dead coordinator demotes the
        swarm, it never strands it."""
        now = get_dht_time()
        if now < self._plan_next_refresh:
            return
        self._plan_next_refresh = now + self.plan_refresh_period
        record, reason = fetch_plan(self.dht, self.prefix)
        if record is not None:
            self._plan_fetch_failures = 0
            self._adopt_plan_record(record)
            return
        if reason == "no plan record published":
            # definitive absence, not a failure: the coordinator simply has
            # not published (or its record expired intentionally) — a bare
            # swarm stays on whatever plan it holds
            self._plan_fetch_failures = 0
            return
        self._plan_fetch_failures += 1
        if self._plan_fetch_failures < MAX_PLAN_FETCH_FAILURES:
            logger.warning(
                f"plan refresh failed ({self._plan_fetch_failures}/"
                f"{MAX_PLAN_FETCH_FAILURES}): {reason} — keeping current plan"
            )
            return
        if self._topology_plan is not None:
            tele = telemetry.resolve(self.telemetry)
            if tele is not None:
                tele.counter("avg.topology.fallbacks").inc()
                tele.event(
                    "avg.topology.fallback", round_id="",
                    reason=(
                        f"plan refresh failed {self._plan_fetch_failures}x"
                        f" consecutively ({reason}) — degrading to flat"
                    ),
                )
            logger.warning(
                f"degrading to flat topology: {self._plan_fetch_failures} "
                f"consecutive plan fetch failures (last: {reason})"
            )
            self._topology_plan = None
        # forget the held (epoch, issued) watermark so a recovered
        # coordinator's republish of the SAME record is re-adoptable
        self._plan_epoch = 0
        self._plan_issued = float("-inf")

    def _adopt_plan_record(self, record) -> None:
        """Adopt ``record`` iff it is newer than what we hold: a higher
        epoch (structural re-plan — new matchmaking scopes), or the same
        epoch with a newer ``issued`` stamp (a tuning-only republish: the
        actuated retune's distribution channel, no scope reshuffle)."""
        newer = record.epoch > self._plan_epoch or (
            record.epoch == self._plan_epoch
            and record.issued > self._plan_issued
        )
        if not newer:
            return
        structural = record.epoch != self._plan_epoch
        self._plan_epoch = int(record.epoch)
        self._plan_issued = float(record.issued)
        self.plan_tuning = dict(record.tuning or {})
        chunk = self.plan_tuning.get("chunk_size")
        if isinstance(chunk, (int, float)) and not isinstance(chunk, bool) \
                and int(chunk) > 0:
            # groups negotiate min-of-advertised chunk geometry, so a
            # staggered rollout of a new size stays wire-compatible
            self.chunk_size = int(chunk)
        if structural:
            self.set_topology_plan(record.topology_plan())

    def _hier_future(self, key: str) -> asyncio.Future:
        """The fan-out future for one round's final result — created by
        whichever side (delegate publish, member pull) gets there first,
        and bounded like _RoundState entries so a key whose delegate never
        publishes cannot leak."""
        fut = self._hier_results.get(key)
        if fut is None:
            fut = asyncio.get_running_loop().create_future()
            self._hier_results[key] = fut
            asyncio.get_running_loop().call_later(
                self.averaging_timeout * 2, self._hier_results.pop, key, None
            )
        return fut

    async def _rpc_hier_final(self, peer, args) -> dict:
        """A clique member pulls the round's final averaged vector from its
        delegate (awaits until the delegate's WAN round lands). The reply
        serves the delegate's cached wire encoding — one encode serves the
        whole clique. A failed WAN leg parks an exception here, so members
        fail FAST into the flat retry ladder instead of idling out their
        timeout."""
        fut = self._hier_future(str(args["round_id"]))
        wire, group_size, contributors = await asyncio.wait_for(
            asyncio.shield(fut), timeout=self.averaging_timeout
        )
        return {
            "data": wire,
            "group_size": group_size,
            "contributors": contributors,
        }

    async def _step_hier(
        self, tree, weight: float, round_id: str,
        expected_size: Optional[int],
        window: Optional[float],
        plan: TopologyPlan,
    ) -> Tuple[Optional[Dict[str, np.ndarray]], int]:
        """One two-level round (averaging/topology.py): clique members
        reduce over cheap local links first (SUM mode — the raw weighted
        sum and its total weight), the clique's delegate carries that
        weight-summed contribution into the WAN butterfly round with
        ``weight=1, norm_weight=W_clique`` (the WAN mean divides by every
        gradient the sum carries without re-scaling it — delegation does
        not change the math), and the result fans back out through the
        delegate's ``avg.final``. Any failure at any rung — clique
        matchmaking, the sum round, the WAN leg, a dead delegate — falls
        back to ONE flat round of the same round_id (the PR 3 overlap
        failure-ladder contract: the flat buffer still holds this peer's
        grads, so the retry re-contributes them unchanged)."""
        from dedloc_tpu.averaging.device_flat import FlatFetch

        tele = telemetry.resolve(self.telemetry)
        my_key = endpoint_key(self.endpoint) if self.endpoint else None
        assignment = plan.assignment([my_key] if my_key else [])

        async def fallback(reason: str, fetched_tree):
            if tele is not None:
                tele.counter("avg.topology.fallbacks").inc()
                tele.event(
                    "avg.topology.fallback", round_id=round_id,
                    reason=reason,
                )
            return await self._step_flat(
                fetched_tree, weight, round_id, expected_size, window
            )

        if assignment is None:
            # a peer with no routable identity cannot be placed in a clique
            return await fallback("no identity in plan", tree)
        clique = assignment.clique
        # fan-out key embeds the (epoch-qualified) clique scope: a member
        # and its delegate only exchange it when they formed the same
        # epoch's clique group, so mixed-epoch rollouts can never cross
        fan_key = f"{self.prefix}:{round_id}:fan:{plan.clique_scope(clique)}"

        # device-flat contribution: resolve the D2H transfer concurrently
        # with the clique matchmaking, same as the flat path
        fetch = None
        if isinstance(tree, FlatFetch):
            fetch = tree
            tree = None
            resolve_task = asyncio.get_running_loop().run_in_executor(
                None, fetch.result
            )
        schema = (
            spec_fingerprint(fetch.spec) if fetch is not None
            else schema_fingerprint(tree)
        )

        async def settle() -> bool:
            """Resolve the in-flight device fetch (idempotent); False when
            the D2H failed — that loses the round on every path."""
            nonlocal tree
            if fetch is not None and tree is None:
                try:
                    tree = await resolve_task
                except Exception as e:  # noqa: BLE001 — one round lost,
                    # never the training process
                    logger.warning(
                        f"{round_id}: device-flat fetch failed: {e!r}"
                    )
                    return False
            return True

        # ---- level 1: the clique-local SUM round over cheap links
        group = None
        if assignment.clique_size > 1:
            try:
                group = await self.matchmaking.form_group(
                    round_id, schema=schema,
                    expected_size=assignment.clique_size,
                    # epoch-qualified scope: peers on different plan epochs
                    # form disjoint groups during a re-plan rollout
                    window=window, scope=plan.clique_scope(clique),
                )
            except MatchmakingFailed as e:
                logger.debug(f"clique matchmaking failed for {round_id}: {e}")
                if not await settle():
                    self.last_contributors = 0
                    return None, 1
                return await fallback("clique matchmaking failed", tree)
        if not await settle():
            self.last_contributors = 0
            return None, 1
        flat = self._flatten(tree)

        sum_vec: Optional[np.ndarray] = None
        w_sum = weight
        delegate_ep = None
        clique_members = 1
        clique_contributors = 0 if (self.auxiliary or weight <= 0) else 1
        if group is not None and len(group.members) > 1:
            delegate_idx = next(
                (
                    i for i, m in enumerate(group.members)
                    if m.endpoint is not None
                    and endpoint_key(m.endpoint) == clique.delegate
                ),
                None,
            )
            if delegate_idx is None and not assignment.is_delegate:
                # the peer that must carry our sum up never joined: there
                # is nobody to pull the WAN result from
                return await fallback("delegate absent from clique", tree)
            if delegate_idx is not None:
                delegate_ep = group.endpoints[delegate_idx]
            clique_members = len(group.members)
            clique_contributors = group.contributors
            try:
                sum_vec, w_sum = await self.allreduce.run(
                    f"{self.prefix}:{round_id}:{group.nonce}",
                    group.my_index, flat, weight,
                    group.endpoints, group.bandwidths,
                    chunk_size=group.chunk_size,
                    normalize=False,
                )
            except AllreduceFailed as e:
                logger.warning(f"clique sum failed for {round_id}: {e}")
                return await fallback("clique sum round failed", tree)
            # the clique SUM leg is the receipt-bearing leg: every member
            # (the delegate included) countersigns the declared weights it
            # just reduced — the WAN leg carries pre-summed vectors whose
            # weights are the norm_weight artifice, not peer declarations
            self._emit_receipt(group, round_id, "clique")
        # else: singleton clique (or nobody joined a delegate's round) —
        # this peer IS its whole contribution and rides the WAN directly

        # ---- level 2, member side: the delegate carries our sum up; pull
        # the final result back from it
        if not assignment.is_delegate:
            if delegate_ep is None:
                return await fallback("no delegate to pull from", tree)
            try:
                reply = await self.client.call(
                    delegate_ep, "avg.final", {"round_id": fan_key},
                    timeout=self.averaging_timeout,
                )
                averaged = deserialize_array(reply["data"])
                if averaged.size != flat.size:
                    raise ValueError(
                        f"fan-out size mismatch: got {averaged.size}, "
                        f"want {flat.size}"
                    )
            except (RPCError, ConnectionError, OSError, ValueError,
                    asyncio.TimeoutError) as e:
                logger.warning(f"{round_id}: delegate fan-out failed: {e!r}")
                return await fallback("delegate died mid-round", tree)
            group_size = int(reply.get("group_size", clique_members))
            self.last_group_size = group_size
            self.last_contributors = int(
                reply.get("contributors", clique_contributors)
            )
            if tele is not None:
                tele.counter("avg.topology.rounds").inc()
                tele.event(
                    "avg.topology.round", round_id=round_id, role="member",
                    clique_size=clique_members, group_size=group_size,
                    ok=True,
                )
            return self._layout.tree_view(averaged), group_size

        # ---- level 2, delegate side: the WAN butterfly among delegates
        fut = self._hier_future(fan_key)
        wan_members = 1
        wan_contributors = 0
        try:
            if faults._active is not None:  # fault injection (testing/faults.py)
                fault = faults.fire(
                    "averager.hier_wan", round_id=round_id,
                    delegate=my_key or "",
                )
                if fault is not None:
                    await faults.apply_transport_fault(fault, "hier WAN leg")
            wan_group = await self.matchmaking.form_group(
                round_id, schema=schema,
                expected_size=assignment.wan_size, window=window,
                scope=plan.wan_scope(),
            )
            wan_members = len(wan_group.members)
            wan_contributors = wan_group.contributors
            if wan_members == 1:
                if sum_vec is not None and w_sum > 0:
                    # alone on the WAN: the clique mean IS the global mean
                    # (scale by the reciprocal — the identical arithmetic
                    # the flat host's finalize applies)
                    averaged = sum_vec * np.float32(1.0 / w_sum)
                elif clique_members == 1:
                    # overall singleton round: flat singleton semantics
                    if not fut.done():
                        fut.set_exception(
                            AllreduceFailed("singleton hierarchical round")
                        )
                    self.last_group_size = 1
                    self.last_contributors = clique_contributors
                    return (tree if weight > 0 else None), 1
                else:
                    averaged = None  # all-zero-weight clique, alone on WAN
            elif sum_vec is not None:
                averaged = await self.allreduce.run(
                    f"{self.prefix}:{round_id}:{wan_group.nonce}",
                    wan_group.my_index, sum_vec,
                    1.0 if w_sum > 0 else 0.0,
                    wan_group.endpoints, wan_group.bandwidths,
                    chunk_size=wan_group.chunk_size,
                    norm_weight=w_sum,
                )
            else:
                # singleton clique: plain (flat-semantics) contribution
                averaged = await self.allreduce.run(
                    f"{self.prefix}:{round_id}:{wan_group.nonce}",
                    wan_group.my_index, flat, weight,
                    wan_group.endpoints, wan_group.bandwidths,
                    chunk_size=wan_group.chunk_size,
                )
        except (MatchmakingFailed, AllreduceFailed, ConnectionError,
                OSError) as e:
            logger.warning(f"{round_id}: WAN leg failed: {e!r}")
            if not fut.done():
                # park the failure for the clique: members fail fast into
                # their own flat retry instead of idling out a timeout
                fut.set_exception(
                    AllreduceFailed(f"delegate WAN leg failed: {e!r}")
                )
            return await fallback("wan leg failed", tree)
        if averaged is None:
            if not fut.done():
                fut.set_exception(AllreduceFailed("nothing to average"))
            self.last_group_size = clique_members
            self.last_contributors = clique_contributors
            return None, clique_members
        # every replica must adopt bit-identical values: the clique decodes
        # the fan-out WIRE bytes, so the delegate adopts its own result
        # through the same codec (the flat path's wire_roundtrip contract)
        wire = serialize_array(averaged, self.compression, checksum=True)
        averaged = deserialize_array(wire)
        group_size = clique_members + wan_members - 1
        contributors = clique_contributors + max(
            0, wan_contributors - (0 if self.auxiliary else 1)
        )
        if not fut.done():
            fut.set_result((wire, group_size, contributors))
        self.last_group_size = group_size
        self.last_contributors = contributors
        if tele is not None:
            tele.counter("avg.topology.rounds").inc()
            tele.event(
                "avg.topology.round", round_id=round_id, role="delegate",
                clique_size=clique_members, wan_size=wan_members,
                group_size=group_size, ok=True,
            )
        return self._layout.tree_view(averaged), group_size

    # --------------------------------------------------------- state sharing

    def set_shared_state(
        self, tree: Dict[str, np.ndarray], metadata: Dict[str, Any]
    ) -> None:
        """Snapshot current training state for late joiners
        (load_state_from_peers counterpart, albert/run_trainer.py:124-128).
        Stores references only — serialization is deferred to the moment a
        peer actually requests the state (off the training thread)."""
        with self._state_lock:
            self._shared_state = (tree, metadata)
            self._shared_state_blob = None  # invalidate serialized cache
            self._sharded_state = None  # and the sharded form
            self._sharded_state_error = None

    def _serve_span(self, name: str, **attrs):
        """Server-side serve span for a state/checkpoint RPC handler: under
        the trace context the dispatch adopted off the request frame, its
        remote parent is the calling peer's span (state_sync attempt,
        ckpt.restore), so --trace shows the provider-side half of every
        download hop. Null span when telemetry is off."""
        tele = telemetry.resolve(self.telemetry)
        return (
            tele.span(name, **attrs)  # dedlint: emits=span:state.serve,span:ckpt.manifest.serve,span:ckpt.shard.serve
            if tele is not None
            else telemetry.null_span()
        )

    async def _rpc_state_get(self, peer, args) -> dict:
        with self._serve_span(
            "state.serve", schema_only=bool(args.get("schema_only"))
        ) as ctx:
            try:
                reply = await self._rpc_state_get_inner(peer, args)
            except Exception as e:
                ctx["ok"] = False
                ctx["error"] = type(e).__name__
                raise
            ctx["ok"] = True
            if "state" in reply:
                ctx["bytes"] = len(reply["state"])
            return reply

    async def _rpc_state_get_inner(self, peer, args) -> dict:
        if not self.allow_state_sharing:
            raise PermissionError("state sharing disabled on this peer")
        with self._state_lock:
            snapshot = self._shared_state
            blob = self._shared_state_blob
        if snapshot is None:
            raise FileNotFoundError("no state snapshot available yet")
        if args.get("schema_only"):
            # tensor names+shapes only (a few KB): what an aux peer needs to
            # bootstrap its gradient template without downloading the full
            # params+optimizer blob (hundreds of MB for real models)
            tree, _metadata = snapshot
            return {
                "schema": {k: list(v.shape) for k, v in tree.items()}
            }
        if blob is None:
            tree, metadata = snapshot

            def _serialize() -> Tuple[bytes, bytes]:
                data = pack_obj(
                    {
                        "metadata": pack_obj(metadata),
                        "tree": serialize_tree(tree, CompressionType.NONE),
                    }
                )
                # digest computed once at serialization time (the blob can be
                # hundreds of MB; rehashing per request would be pure waste)
                return data, hashlib.sha256(data).digest()

            # off the event loop (serializing the full model+optimizer state
            # can take seconds and must not stall live matchmaking/allreduce),
            # and deduplicated: concurrent late joiners await ONE serialization
            if self._serialize_task is None or self._serialize_task.done():
                loop = asyncio.get_running_loop()
                self._serialize_task = asyncio.ensure_future(
                    loop.run_in_executor(None, _serialize)
                )
            blob = await asyncio.shield(self._serialize_task)
            with self._state_lock:
                if self._shared_state is snapshot:  # not replaced meanwhile
                    self._shared_state_blob = blob
        data, digest = blob
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("state.served").inc()
            tele.counter("state.served_bytes").inc(len(data))
        if faults._active is not None:  # fault injection (testing/faults.py)
            fault = faults.fire("averager.state_get", size=len(data))
            if fault is not None and fault.action == "truncate":
                # truncated download: the digest stays that of the FULL blob,
                # so the receiver's checksum validation catches the cut
                data = data[: int(len(data) * fault.fraction)]
                if tele is not None:
                    # attribute the APPLIED fault to the SERVING peer — the
                    # downloader sees only a checksum failure
                    tele.counter("faults.applied").inc()
                    tele.event(
                        "fault.applied", point="averager.state_get",
                        action="truncate", fraction=fault.fraction,
                    )
        return {"state": data, "checksum": digest}

    # ---------------------------------------------------- sharded state serving

    def _sharded_state_sync(
        self,
    ) -> Optional[Tuple[CheckpointManifest, np.ndarray]]:
        """Build (or return the cached) sharded form of the current shared
        state: manifest + fresh flat fp32 vector. Thread-safe and idempotent
        — callable from the backup thread (catalog publish) and from the
        DHT loop's executor (first ckpt RPC); a rare concurrent double
        build computes the identical result. Returns None when there is no
        snapshot; raises ValueError when the tree cannot roundtrip through
        the fp32 layout (callers then stay blob-only)."""
        if self.checkpoint_shard_size <= 0:
            return None
        with self._state_lock:
            snapshot = self._shared_state
            cached = self._sharded_state
            failed = self._sharded_state_error
        if snapshot is None:
            return None
        if cached is not None:
            return cached
        if failed is not None and failed[0] is snapshot:
            # this exact snapshot already failed the roundtrip check —
            # re-raise without paying the full-state flatten again
            raise ValueError(failed[1])
        tree, metadata = snapshot
        step = int(metadata.get("local_step", metadata.get("step", 0)) or 0)
        try:
            built = build_manifest(
                tree, step, shard_size=self.checkpoint_shard_size,
                metadata=metadata,
            )
        except ValueError as e:
            # warn ONCE per snapshot (here, at build time); cached retries
            # and the publish cadence stay silent
            logger.warning(f"sharded checkpoint serving unavailable: {e}")
            with self._state_lock:
                if self._shared_state is snapshot:
                    self._sharded_state_error = (snapshot, str(e))
            raise
        with self._state_lock:
            if self._shared_state is snapshot:  # not replaced meanwhile
                self._sharded_state = built
        return built

    async def _sharded_snapshot(self) -> Tuple[CheckpointManifest, np.ndarray]:
        """Sharded snapshot for the RPC handlers: built off the event loop
        (flatten + sha256 over the full state takes seconds at real model
        sizes) and deduplicated like the blob serialization."""
        if not self.allow_state_sharing:
            raise PermissionError("state sharing disabled on this peer")
        if self.checkpoint_shard_size <= 0:
            raise FileNotFoundError("sharded checkpoints disabled on this peer")
        with self._state_lock:
            cached = self._sharded_state
        if cached is not None:
            return cached
        if self._shard_task is None or self._shard_task.done():
            loop = asyncio.get_running_loop()
            self._shard_task = asyncio.ensure_future(
                loop.run_in_executor(None, self._sharded_state_sync)
            )
        built = await asyncio.shield(self._shard_task)
        if built is None:
            raise FileNotFoundError("no state snapshot available yet")
        return built

    async def _rpc_ckpt_manifest(self, peer, args) -> dict:
        with self._serve_span("ckpt.manifest.serve") as ctx:
            try:
                manifest, _flat = await self._sharded_snapshot()
            except Exception as e:
                ctx["ok"] = False
                ctx["error"] = type(e).__name__
                raise
            ctx["ok"] = True
            ctx["step"] = manifest.step
            return {"manifest": manifest.to_bytes()}

    async def _rpc_ckpt_shard(self, peer, args) -> dict:
        with self._serve_span(
            "ckpt.shard.serve", shard=int(args.get("index", -1))
        ) as ctx:
            try:
                reply = await self._rpc_ckpt_shard_inner(peer, args)
            except Exception as e:
                ctx["ok"] = False
                ctx["error"] = type(e).__name__
                raise
            ctx["ok"] = True
            ctx["bytes"] = len(reply["data"])
            return reply

    async def _rpc_ckpt_shard_inner(self, peer, args) -> dict:
        manifest, flat = await self._sharded_snapshot()
        index = int(args["index"])
        raw = shard_bytes(flat, manifest, index)
        if faults._active is not None:  # fault injection (testing/faults.py)
            fault = faults.fire("checkpoint.shard_get", index=index,
                                size=len(raw))
            if fault is not None and fault.action == "truncate":
                # the manifest digest stays that of the FULL shard, so the
                # fetcher's per-shard verification catches the cut; keep the
                # cut fp32-aligned so frombuffer below still parses and the
                # failure surfaces as a VERIFY failure, not a server crash
                cut = int(len(raw) * fault.fraction)
                raw = raw[: cut - cut % 4]
                tele_f = telemetry.resolve(self.telemetry)
                if tele_f is not None:
                    tele_f.counter("faults.applied").inc()
                    tele_f.event(
                        "fault.applied", point="checkpoint.shard_get",
                        action="truncate", shard=index,
                    )
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            tele.counter("ckpt.shards_served").inc()
            tele.counter("ckpt.shard_bytes_served").inc(len(raw))
        return {
            "index": index,
            "data": serialize_array(
                np.frombuffer(raw, dtype=np.float32), CompressionType.NONE
            ),
        }

    # ------------------------------------------------ contribution ledger

    def _ledger_subkey(self) -> bytes:
        """The slot this peer's ledger records ride: the signed owner tag
        when it speaks for this peer's id (subkey_owner_id — always true
        for roles-built peers, whose validator key IS the identity key),
        else the raw peer id, which binds structurally. Either way the
        coordinator's parse path can verify the record speaks for exactly
        this peer; a subkey that binds to somebody else would get every
        record silently dropped at the fold."""
        from dedloc_tpu.telemetry.ledger import subkey_owner_id

        sk = self.signed_subkey
        if sk is not None and subkey_owner_id(sk) == self.peer_id.hex():
            return sk
        return self.peer_id

    def _emit_receipt(self, group: GroupInfo, round_id: str,
                      leg: str) -> None:
        """Countersign a finalized round: fold the group's declared weights
        into this peer's cumulative witness table and republish its signed
        ``RoundReceipt`` DHT record (telemetry/ledger.py). Runs on the DHT
        loop right after the leg's all-reduce lands. Best-effort by
        contract: accounting must never cost the round that just
        succeeded."""
        if not self.ledger_receipts or len(group.members) < 2:
            return
        try:
            member_weights = [
                (m.peer_id.hex(), float(m.weight)) for m in group.members
            ]
            receipt = receipt_from_group(
                self.peer_id.hex(), round_id,
                parse_round_step(round_id), leg,
                member_weights, self._ledger_witness,
            )
            publish_receipt(
                self.dht, self.prefix, self._ledger_subkey(), receipt,
            )
            tele = telemetry.resolve(self.telemetry)
            if tele is not None:
                tele.counter("ledger.receipts").inc()
                # the full receipt rides the event (hex ids, cumulative
                # witness included), so an event-log-only fold reconstructs
                # the same supported totals the DHT fold would
                tele.event(
                    "ledger.receipt", round_id=round_id, leg=leg,
                    signer=receipt.signer, step=receipt.step,
                    members=receipt.members, weights=receipt.weights,
                    witness={
                        p: {"samples": e.samples, "rounds": e.rounds}
                        for p, e in receipt.witness.items()
                    },
                )
        except Exception as e:  # noqa: BLE001 — see docstring
            logger.warning(f"{round_id}: receipt publish failed: {e!r}")

    def publish_contribution_claim(
        self, samples: int, rounds: int, train_seconds: float,
        expiration: float = 300.0,
    ) -> None:
        """Publish this peer's cumulative ``ContributionClaim`` DHT record
        (schema-validated at every storing node; signature-bound when a
        signed subkey was given). ``samples``/``rounds`` come from the
        collaborative optimizer's cumulative counters; serve bytes read
        straight off the existing ckpt/state counters, so a provider's
        serving contribution needs no second bookkeeping."""
        bytes_served = 0
        tele = telemetry.resolve(self.telemetry)
        if tele is not None:
            bytes_served = int(
                tele.counter("ckpt.shard_bytes_served").value
                + tele.counter("state.served_bytes").value
            )
        try:
            claim = ContributionClaim(
                peer=self.peer_id.hex(),
                samples=int(samples),
                rounds=int(rounds),
                train_seconds=float(max(0.0, train_seconds)),
                bytes_served=bytes_served,
                time=get_dht_time(),
            )
            publish_claim(
                self.dht, self.prefix, self._ledger_subkey(), claim,
                expiration=expiration,
            )
        except Exception as e:  # noqa: BLE001 — accounting must never
            # cost a training step
            logger.warning(f"contribution claim publish failed: {e!r}")
            return
        if tele is not None:
            tele.counter("ledger.claims").inc()
            tele.event(
                "ledger.claim", peer=claim.peer, samples=claim.samples,
                rounds=claim.rounds,
                train_seconds=round(claim.train_seconds, 3),
                bytes_served=claim.bytes_served,
            )

    def publish_checkpoint_announcement(
        self, expiration: float = 60.0
    ) -> None:
        """Announce this peer's sharded checkpoint on the DHT catalog
        (schema-validated; signature-bound when a signed subkey was given).
        A full-state provider holds ALL shards, so ``shards`` is None."""
        if (
            self.checkpoint_shard_size <= 0
            or not self.allow_state_sharing
            or self.endpoint is None
        ):
            return
        try:
            built = self._sharded_state_sync()
        except ValueError as e:
            # tree not representable in the fp32 flat layout: blob-only peer
            # (_sharded_state_sync warned once at build time)
            logger.debug(f"sharded checkpoint serving unavailable: {e}")
            return
        if built is None:
            return
        manifest, _flat = built
        announcement = CheckpointAnnouncement(
            step=manifest.step,
            manifest_digest=manifest.digest(),
            num_shards=manifest.num_shards,
            endpoint=list(self.endpoint),
            shards=None,
        )
        publish_announcement(
            self.dht,
            self.prefix,
            self.signed_subkey or self.peer_id,
            announcement,
            expiration=expiration,
        )

    def publish_state_provider(
        self, expiration: float = 60.0, step: int = 0
    ) -> None:
        """Advertise this peer as a state provider, with its global step so
        joiners can prefer the NEWEST snapshot."""
        if not self.allow_state_sharing or self.endpoint is None:
            return
        self.dht.store(
            f"{self.prefix}_state_providers",
            {"endpoint": list(self.endpoint), "step": int(step)},
            get_dht_time() + expiration,
            subkey=self.peer_id,
        )
        # sharded serving rides the same publish cadence: the catalog
        # record carries the manifest digest, so building the sharded form
        # here (on the caller's backup thread, off the training path) also
        # pre-warms what the ckpt RPCs will serve
        self.publish_checkpoint_announcement(expiration=expiration)

    def fetch_state_schema(
        self, timeout: float = 15.0
    ) -> Optional[Dict[str, tuple]]:
        """{tensor name: shape} from any live state provider — the cheap
        (KB-sized) sibling of ``load_state_from_peers`` for peers that need
        only the tree's structure (aux template bootstrap)."""
        providers = self._live_state_providers()

        def _fetch(node):
            async def fetch():
                for ep in providers:
                    try:
                        reply = await self.client.call(
                            ep, "state.get", {"schema_only": True},
                            timeout=timeout,
                        )
                        return {
                            k: tuple(v) for k, v in reply["schema"].items()
                        }
                    except Exception as e:  # noqa: BLE001 — next provider
                        logger.debug(f"schema fetch from {ep} failed: {e!r}")
                return None

            return fetch()

        return self.dht.run_coroutine(_fetch)

    def _provider_records(self, entry_items) -> List[Tuple[int, tuple]]:
        """THE one parsing path for state-provider advertisements: skip our
        own record, extract (step, endpoint), drop malformed entries.
        ``_live_state_providers``, ``best_advertised_state_step`` and the
        in-loop retry refresh all derive from it, so the views cannot drift
        apart on a future record-format change (advisor r5). ``entry_items``
        is an iterable of (subkey, unpacked advertisement dict)."""
        records: List[Tuple[int, tuple]] = []
        for sk, value in entry_items:
            if sk == getattr(self, "peer_id", None):
                continue
            try:
                records.append(
                    (int(value.get("step", 0)), tuple(value["endpoint"]))
                )
            except Exception:  # noqa: BLE001 — malformed advertisement
                continue
        return records

    def _advertised_state_records(self) -> List[Tuple[int, tuple]]:
        """(step, endpoint) of every OTHER live provider, from the caller
        thread (blocking DHT lookup)."""
        entry = self.dht.get(f"{self.prefix}_state_providers", latest=True)
        if entry is None or not hasattr(entry.value, "items"):
            return []
        return self._provider_records(
            (sk, v.value) for sk, v in entry.value.items()
        )

    async def _advertised_state_records_async(
        self, node
    ) -> List[Tuple[int, tuple]]:
        """Same view, from ON the DHT loop (retry attempts refresh the
        provider list without a cross-thread round trip)."""
        entry = await node.get(
            f"{self.prefix}_state_providers".encode(), latest=True
        )
        items = []
        if entry is not None and hasattr(entry.value, "items"):
            for sk, v in entry.value.items():
                try:
                    items.append((sk, unpack_obj(v.value)))
                except Exception:  # noqa: BLE001 — undecodable entry
                    continue
        return self._provider_records(items)

    def _live_state_providers(self):
        candidates = self._advertised_state_records()
        # newest snapshot first — a stale provider must not win the race
        candidates.sort(key=lambda c: -c[0])
        return [ep for _step, ep in candidates]

    def _own_catalog_subkeys(self) -> tuple:
        return tuple(
            sk
            for sk in (getattr(self, "peer_id", None), self.signed_subkey)
            if sk is not None
        )

    def _catalog_records(self) -> List[CheckpointAnnouncement]:
        """Every OTHER peer's checkpoint-catalog announcement, from the
        caller thread (blocking DHT lookup)."""
        entry = self.dht.get(catalog_key(self.prefix), latest=True)
        if entry is None or not hasattr(entry.value, "items"):
            return []
        return parse_announcements(
            ((sk, v.value) for sk, v in entry.value.items()),
            own_subkeys=self._own_catalog_subkeys(),
        )

    async def _catalog_records_async(
        self, node
    ) -> List[CheckpointAnnouncement]:
        """Same view, from ON the DHT loop (the restore path runs there)."""
        entry = await node.get(catalog_key(self.prefix).encode(), latest=True)
        items = []
        if entry is not None and hasattr(entry.value, "items"):
            for sk, v in entry.value.items():
                try:
                    items.append((sk, unpack_obj(v.value)))
                except Exception:  # noqa: BLE001 — undecodable entry
                    continue
        return parse_announcements(
            items, own_subkeys=self._own_catalog_subkeys()
        )

    def best_advertised_state_step(self) -> Optional[int]:
        """Deepest global step any live provider ADVERTISES in its KB-sized
        DHT record (full-blob provider records AND checkpoint-catalog
        announcements) — lets a resumed peer decide whether a download
        could possibly be newer than its checkpoint without pulling the
        full multi-hundred-MB state. None when nobody shares."""
        steps = [step for step, _ep in self._advertised_state_records()]
        if self.checkpoint_shard_size > 0:
            steps += [a.step for a in self._catalog_records()]
        return max(steps) if steps else None

    async def _try_sharded_restore(
        self, node, tele, timeout: float, retries: int, backoff: float
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
        """Multi-peer sharded restore attempt (runs on the DHT loop). Any
        failure — no catalog, unobtainable manifest, a shard exhausting its
        ladder — returns None and the caller falls back to the full-blob
        path; the ``ckpt.restore`` span records the outcome either way."""
        announcements = await self._catalog_records_async(node)
        if not announcements:
            return None
        with telemetry.span(
            "ckpt.restore", self.telemetry, mode="sharded"
        ) as ctx:
            stats: Dict[str, Any] = {}
            try:
                metadata, tree, manifest = await sharded_restore(
                    self.client,
                    announcements,
                    parallelism=self.checkpoint_fetch_parallelism,
                    retries=retries,
                    backoff=backoff,
                    timeout=timeout,
                    store=self._ckpt_store,
                    max_providers=self.checkpoint_max_providers,
                    telemetry_registry=self.telemetry,
                    stats=stats,
                )
            except Exception as e:  # noqa: BLE001 — RestoreFailed et al.
                ctx["ok"] = False
                ctx["error"] = type(e).__name__
                if tele is not None:
                    tele.counter("ckpt.restore_failures").inc()
                logger.warning(
                    f"sharded restore failed ({e!r}); falling back to the "
                    "full-blob state path"
                )
                return None
            ctx["ok"] = True
            ctx["step"] = manifest.step
            ctx["shards"] = manifest.num_shards
            ctx["bytes"] = manifest.total_bytes
            # providers ACTUALLY pulled from (selected step/digest, capped),
            # not the raw announcement count with stale/outvoted peers in it
            ctx["providers"] = stats.get("providers", 0)
            if stats.get("provider_bytes"):
                # verified bytes per provider endpoint: which uplinks this
                # restore actually rode (fast-provider preference input)
                ctx["provider_bytes"] = stats["provider_bytes"]
            if tele is not None:
                tele.counter("ckpt.restores").inc()
            return metadata, tree

    def load_state_from_peers(
        self,
        timeout: float = 60.0,
        retries: Optional[int] = None,
        backoff: Optional[float] = None,
    ) -> Optional[Tuple[Dict[str, Any], Dict[str, np.ndarray]]]:
        """Download (metadata, tree) from a live state provider.

        Restore preference order (docs/fleet.md restart runbook): the
        SHARDED path first — when the checkpoint catalog announces a
        manifest, distinct shards are pulled from distinct providers in
        parallel with per-shard sha256 verification (checkpointing/fetcher)
        — then the single-provider full-blob ladder below as fallback.

        Peer-lifecycle robustness contract (``state_sync_retries`` /
        ``state_sync_backoff``): the download is retried with exponential
        backoff, each attempt re-reads the DHT provider list (a provider
        that registered between attempts is picked up) and prefers providers
        that have not already failed — so a dead or corrupt provider costs
        one backoff, not the whole join. When EVERY known provider has
        failed once, they are all retried anyway: a transient fault on the
        only provider must not permanently fail the sync. Each received
        snapshot is checksum-validated before deserialization, so a
        truncated or corrupt download is detected and retried instead of
        exploding mid-unpack (or silently adopting garbage)."""
        retries = self.state_sync_retries if retries is None else retries
        backoff = self.state_sync_backoff if backoff is None else backoff

        def _fetch(node):
            async def fetch():
                tele = telemetry.resolve(self.telemetry)
                if self.checkpoint_shard_size > 0:
                    result = await self._try_sharded_restore(
                        node, tele, timeout, retries, backoff
                    )
                    if result is not None:
                        return result
                failed: set = set()
                for attempt in range(retries + 1):
                    if attempt:
                        delay = backoff * (2 ** (attempt - 1))
                        if tele is not None:
                            # retry/backoff trace: the coordinator's retry-
                            # rate view is built from these counters
                            tele.counter("state_sync.retries").inc()
                            tele.event(
                                "state_sync.retry", attempt=attempt,
                                backoff_s=delay,
                            )
                        await asyncio.sleep(delay)
                    records = await self._advertised_state_records_async(node)
                    records.sort(key=lambda c: -c[0])  # newest first
                    providers = [ep for _step, ep in records]
                    untried = [ep for ep in providers if ep not in failed]
                    for ep in untried or providers:
                        try:
                            if tele is not None:
                                tele.counter("state_sync.attempts").inc()
                            reply = await self.client.call(
                                ep, "state.get", {}, timeout=timeout
                            )
                            blob = reply["state"]
                            digest = reply.get("checksum")
                            if (
                                digest is not None
                                and hashlib.sha256(blob).digest() != digest
                            ):
                                if tele is not None:
                                    tele.counter(
                                        "state_sync.checksum_failures"
                                    ).inc()
                                    tele.event(
                                        "state_sync.checksum_failure",
                                        provider=ep, attempt=attempt + 1,
                                        bytes=len(blob),
                                    )
                                raise ValueError(
                                    "state snapshot failed checksum "
                                    "(truncated or corrupt download)"
                                )
                            obj = unpack_obj(blob)
                            if tele is not None:
                                tele.counter("state_sync.ok").inc()
                                tele.event(
                                    "state_sync.ok", provider=ep,
                                    bytes=len(blob), attempt=attempt + 1,
                                )
                            return (
                                unpack_obj(obj["metadata"]),
                                deserialize_tree(obj["tree"]),
                            )
                        except Exception as e:  # noqa: BLE001 — next provider
                            failed.add(ep)
                            if tele is not None:
                                tele.counter("state_sync.failures").inc()
                                tele.event(
                                    "state_sync.failed", provider=ep,
                                    attempt=attempt + 1,
                                    error=type(e).__name__,
                                )
                            logger.debug(
                                f"state fetch from {ep} failed "
                                f"(attempt {attempt + 1}/{retries + 1}): {e!r}"
                            )
                return None

            return fetch()

        return self.dht.run_coroutine(_fetch)

    def shutdown(self) -> None:
        def _stop(node):
            async def stop():
                keepalive = getattr(self, "_relay_keepalive", None)
                if keepalive is not None:
                    keepalive.cancel()
                await self.client.close()
                if self.server is not None:
                    await self.server.stop()

            return stop()

        try:
            self.dht.run_coroutine(_stop)
        except Exception:  # noqa: BLE001 — best effort
            pass
