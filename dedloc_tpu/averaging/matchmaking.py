"""DHT-driven matchmaking: assemble bounded peer groups for each round.

Capability parity with hivemind's DecentralizedAverager matchmaking
(SURVEY.md §2.6: ``target_group_size``, ``averaging_expiration`` straggler
window): peers that decide to average for round R either JOIN an already
declared leader (blocking RPC that returns the assembled group) or DECLARE
themselves leader in the DHT and accept joins until their deadline.

Concurrent leaders are not an error: each assembles its own group, groups
average independently, and group composition rotates across rounds (leader
choice is ranked by hash(round_id, leader_id)) — the same gossip-style
mixing DeDLOC relies on (contributor notebook cell 3: group failure only
costs that group one round).
"""
from __future__ import annotations

import asyncio
import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from dedloc_tpu.core.serialization import pack_obj, unpack_obj
from dedloc_tpu.core.timeutils import get_dht_time
from dedloc_tpu.dht.node import DHTNode
from dedloc_tpu.dht.protocol import Endpoint, RPCClient, RPCServer
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class Member:
    peer_id: bytes
    endpoint: Optional[Endpoint]  # None for client-mode members
    bandwidth: float

    def pack(self) -> list:
        ep = list(self.endpoint) if self.endpoint else None
        return [self.peer_id, ep, self.bandwidth]

    @classmethod
    def unpack(cls, raw) -> "Member":
        ep = tuple(raw[1]) if raw[1] else None
        return cls(raw[0], ep, float(raw[2]))


@dataclass
class GroupInfo:
    round_id: str
    members: List[Member]  # sorted by peer_id — identical on every member
    my_index: int

    @property
    def endpoints(self) -> List[Optional[Endpoint]]:
        return [m.endpoint for m in self.members]

    @property
    def bandwidths(self) -> List[float]:
        # client-mode members host nothing
        return [m.bandwidth if m.endpoint else 0.0 for m in self.members]


class MatchmakingFailed(Exception):
    pass


class Matchmaking:
    """One per peer. Needs the peer's RPC server (None in client mode) and
    its DHTNode (all calls run on the node's event loop)."""

    def __init__(
        self,
        node: DHTNode,
        client: RPCClient,
        server: Optional[RPCServer],
        prefix: str,
        peer_id: bytes,
        endpoint: Optional[Endpoint],
        bandwidth: float,
        target_group_size: int = 256,
        averaging_expiration: float = 5.0,
    ):
        self.node = node
        self.client = client
        self.prefix = prefix
        self.peer_id = peer_id
        self.endpoint = endpoint  # None => client mode
        self.bandwidth = bandwidth if endpoint is not None else 0.0
        self.target_group_size = target_group_size
        self.averaging_expiration = averaging_expiration
        # leader state: round_id -> (members dict, assembled event)
        self._leading: Dict[str, Tuple[Dict[bytes, Member], asyncio.Event]] = {}
        if server is not None:
            server.register("mm.join", self._rpc_join)

    def _leaders_key(self, round_id: str) -> bytes:
        return f"{self.prefix}_leaders_{round_id}".encode()

    # ------------------------------------------------------------- leader

    async def _rpc_join(self, peer: Endpoint, args) -> dict:
        round_id = args["round_id"]
        member = Member.unpack(args["member"])
        entry = self._leading.get(round_id)
        if entry is None:
            raise MatchmakingFailed(f"not leading round {round_id}")
        members, assembled = entry
        if assembled.is_set():
            raise MatchmakingFailed(f"round {round_id} already assembled")
        if len(members) >= self.target_group_size:
            raise MatchmakingFailed(f"round {round_id} is full")
        members[member.peer_id] = member
        await assembled.wait()
        group = sorted(members.values(), key=lambda m: m.peer_id)
        return {"members": [m.pack() for m in group]}

    async def _lead(
        self, round_id: str, deadline: float, allow_abandon: bool
    ) -> Optional[GroupInfo]:
        """Lead a group until ``deadline``. Returns None if leadership was
        abandoned in favour of a better-ranked concurrent leader (only ever
        done while we still have zero followers — atomic w.r.t. the loop)."""
        me = Member(self.peer_id, self.endpoint, self.bandwidth)
        members: Dict[bytes, Member] = {self.peer_id: me}
        assembled = asyncio.Event()
        self._leading[round_id] = (members, assembled)
        my_rank = self._rank(round_id, self.peer_id)
        try:
            await self.node.store(
                self._leaders_key(round_id),
                pack_obj({"endpoint": list(self.endpoint)}),
                deadline,
                subkey=self.peer_id,
            )
            check_period = max(0.05, self.averaging_expiration / 5)
            while True:
                remaining = deadline - get_dht_time()
                if remaining <= 0:
                    break
                await asyncio.sleep(min(check_period, remaining))
                # two peers may have declared simultaneously: the one with
                # the worse rank (and no followers yet) defects and re-joins
                if allow_abandon and len(members) == 1:
                    entry = await self.node.get(
                        self._leaders_key(round_id), latest=True
                    )
                    if entry is not None and hasattr(entry.value, "items"):
                        better = [
                            sk
                            for sk, v in entry.value.items()
                            if sk != self.peer_id
                            and v.expiration_time > get_dht_time()
                            and self._rank(round_id, sk) < my_rank
                        ]
                        if better and len(members) == 1:
                            self._leading.pop(round_id, None)
                            return None
        finally:
            assembled.set()  # joiners get their reply even if store failed
        group = sorted(members.values(), key=lambda m: m.peer_id)
        # let pending join handlers finish serializing before cleanup
        asyncio.get_running_loop().call_later(
            self.averaging_expiration, self._leading.pop, round_id, None
        )
        return GroupInfo(round_id, group, group.index(me))

    # ------------------------------------------------------------ follower

    async def _try_join(self, round_id: str, leader_ep: Endpoint) -> GroupInfo:
        me = Member(self.peer_id, self.endpoint, self.bandwidth)
        reply = await self.client.call(
            leader_ep,
            "mm.join",
            {"round_id": round_id, "member": me.pack()},
            timeout=self.averaging_expiration * 3 + 5.0,
        )
        members = [Member.unpack(r) for r in reply["members"]]
        ids = [m.peer_id for m in members]
        if self.peer_id not in ids:
            raise MatchmakingFailed("leader did not include us")
        return GroupInfo(round_id, members, ids.index(self.peer_id))

    def _rank(self, round_id: str, leader_id: bytes) -> bytes:
        return hashlib.sha256(round_id.encode() + leader_id).digest()

    # ----------------------------------------------------------------- main

    async def _live_leaders(self, round_id: str) -> List[Tuple[bytes, Endpoint]]:
        entry = await self.node.get(self._leaders_key(round_id), latest=True)
        now = get_dht_time()
        leaders: List[Tuple[bytes, Endpoint]] = []
        if entry is not None and hasattr(entry.value, "items"):
            for sk, v in entry.value.items():
                if v.expiration_time <= now:
                    continue
                try:
                    info = unpack_obj(v.value)
                    leaders.append((sk, tuple(info["endpoint"])))
                except Exception:  # noqa: BLE001 — malformed entry
                    continue
        leaders.sort(key=lambda kv: self._rank(round_id, kv[0]))
        return leaders

    async def form_group(self, round_id: str) -> GroupInfo:
        """Join an existing leader or lead; returns the assembled group
        (possibly a singleton if nobody else showed up). Client-mode peers
        cannot lead, so they keep polling for a leader within the straggler
        window instead of failing instantly on a startup race."""
        allow_abandon = True
        deadline = get_dht_time() + self.averaging_expiration * 2
        attempt = 0
        while True:
            attempt += 1
            for leader_id, leader_ep in await self._live_leaders(round_id):
                if leader_id == self.peer_id:
                    continue
                try:
                    return await self._try_join(round_id, leader_ep)
                except Exception as e:  # noqa: BLE001 — try next leader
                    logger.debug(f"join {leader_ep} failed: {e!r}")
                    continue
            if self.endpoint is None:
                if get_dht_time() >= deadline:
                    raise MatchmakingFailed(
                        "client-mode peer found no joinable leader for this round"
                    )
                await asyncio.sleep(
                    min(0.3, max(0.05, self.averaging_expiration / 10))
                )
                continue
            if attempt > 3:
                raise MatchmakingFailed(f"could not form a group for {round_id}")
            lead_deadline = get_dht_time() + self.averaging_expiration
            group = await self._lead(round_id, lead_deadline, allow_abandon)
            if group is not None:
                return group
            allow_abandon = False  # abandoned once; never defect again
