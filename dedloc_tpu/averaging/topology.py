"""Two-level averaging topology planner: low-RTT cliques + elected delegates.

DeDLOC's core contribution (PAPER.md §0) is an averaging algorithm that
ADAPTS between all-reduce, parameter-server and gossip depending on peer
bandwidth and reliability. This module is the decision half of that
adaptation for the TPU build: given the per-directed-link RTT/goodput table
the telemetry layer already measures (``telemetry/links.py``, folded
swarm-wide by ``telemetry/health.build_topology``), it partitions a round's
roster into datacenter-local cliques and elects one delegate per clique by
uplink capacity. The execution half — clique members reduce over cheap
local links first, delegates carry the clique's weight-summed contribution
into the WAN butterfly round, then fan the result back out — lives in
``averaging/averager.py`` (``--averager.hierarchical``).

The paper's degenerate strategies fall out of the same planner instead of
being separate code paths:

- one giant clique covering every peer  ⇒ ``mode="flat"`` (plain all-reduce
  — a second level would only add a hop);
- a sparse or empty link table           ⇒ ``mode="flat"`` (no evidence to
  group by; the runtime keeps today's flat butterfly);
- a few fat listening peers + a crowd of thin client-mode volunteers ⇒ the
  volunteers are attached to the fattest listeners' cliques, which makes
  those delegates de-facto parameter servers.

``clique_groups`` is the shared clique detector — promoted out of
``tools/runlog_summary.py`` (PR 6's ``--topology`` view) so the operator
preview (``--topology`` ``plan`` section) and the runtime planner can never
disagree about what counts as a clique.

Plan identity: member ids are opaque strings. The runtime averager installs
plans whose ids are ENDPOINT KEYS (``"host:port"`` — what matchmaking
members advertise, so a formed group can be matched against the plan); the
operator views built from folded telemetry use peer labels. ``assignment``
accepts any of the caller's known identities.
"""
from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# a clique is "same-datacenter material": pairwise RTT well under the swarm
# median (the PR 6 --topology heuristic, unchanged by the promotion)
CLIQUE_RTT_FACTOR = 0.5
# a fat peer serves >= this multiple of the median uplink capacity — the
# parameter-server degenerate case attaches thin volunteers to these
FAT_UPLINK_FACTOR = 2.0
# when at least this fraction of the roster churns per health fold the
# swarm is "very unreliable": full-swarm rounds keep dying mid-exchange, so
# the planner selects gossip-style neighbor averaging (small deterministic
# groups — a dead partner costs one pair's round, not the swarm's)
GOSSIP_INSTABILITY_THRESHOLD = 0.25
# gossip neighbor-group size: pairs, with one group of 3 on an odd roster
GOSSIP_GROUP_SIZE = 2


def clique_groups(links, dst_key: str = "dst"):
    """(median rtt, clique candidate groups) from directed link records.

    Peers whose pairwise RTT sits well under the swarm median are
    same-datacenter material — the hierarchical planner's local-reduction
    groups (ROADMAP item 1). ``links`` are dicts with ``src``/``dst_key``
    peer ids and an optional ``rtt_s``; groups are the connected components
    of the low-RTT pair graph (union-find), smallest-first sorted for
    determinism. Shared by the runtime planner and ``runlog_summary
    --topology`` (which passes ``dst_key="dst_label"``)."""
    rtts = sorted(
        l["rtt_s"] for l in links if l.get("rtt_s") is not None
    )
    if len(rtts) < 2:
        return None, []
    median_rtt = rtts[len(rtts) // 2]
    fast_pairs = [
        (l["src"], l[dst_key]) for l in links
        if l.get("rtt_s") is not None
        and l["rtt_s"] <= CLIQUE_RTT_FACTOR * median_rtt
    ]
    if not fast_pairs:
        return median_rtt, []
    # union-find over low-RTT pairs
    parent = {}

    def find(x):
        parent.setdefault(x, x)
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in fast_pairs:
        parent[find(a)] = find(b)
    cliques = {}
    for node in parent:
        cliques.setdefault(find(node), set()).add(node)
    return median_rtt, sorted(
        sorted(c) for c in cliques.values() if len(c) >= 2
    )


def uplink_capacity(links, dst_key: str = "dst") -> Dict[str, float]:
    """Per-peer uplink capacity estimate from the link table: the best
    observed outbound rate (``peak_bps`` — the least-contended sample —
    falling back to the ``goodput_bps`` EWMA). The delegate-election
    ranking: the delegate pays the clique's whole WAN exchange over its
    serialized uplink, so the fattest uplink carries it."""
    out: Dict[str, float] = {}
    for l in links:
        src = l.get("src")
        if src is None:
            continue
        rate = l.get("peak_bps", l.get("goodput_bps"))
        if rate is None:
            continue
        out[src] = max(out.get(src, 0.0), float(rate))
    return out


@dataclass
class CliquePlan:
    """One clique: sorted member ids + the elected delegate."""

    members: List[str]
    delegate: str

    def key(self) -> str:
        """Stable 12-hex identity of this clique — the matchmaking scope
        its local rounds form under. Derived from the sorted member set,
        so every peer holding the same plan derives the same scope with no
        extra handshake."""
        return hashlib.sha256(
            "\x00".join(sorted(self.members)).encode()
        ).hexdigest()[:12]


@dataclass
class Assignment:
    """One peer's view of the plan: its clique, its delegate, its role."""

    member_id: str
    clique: CliquePlan
    wan_size: int  # how many parties join the WAN round (cliques + directs)

    @property
    def is_delegate(self) -> bool:
        return self.member_id == self.clique.delegate

    @property
    def clique_size(self) -> int:
        return len(self.clique.members)


@dataclass
class TopologyPlan:
    """The planner's output: ``mode="flat"`` (keep today's butterfly — with
    ``reason`` saying why), ``mode="hierarchical"`` with the clique list, or
    ``mode="gossip"`` with the ``peers`` roster (very-unreliable swarms:
    deterministic neighbor pairs per round instead of full-swarm rounds).
    Serializable (``--averager.topology_plan`` file), and the SAME object
    the ``runlog_summary --topology`` plan section renders.

    ``epoch`` versions live re-planning (roles/coordinator.py publishes an
    epoch-bumped plan record on material topology change; averager peers
    adopt the newest between rounds). Matchmaking scopes embed the epoch —
    see ``clique_scope``/``wan_scope``/``gossip_scope`` — so peers holding
    epoch k and k+1 concurrently form DISJOINT groups during rollout: no
    barrier, no handshake, a stale-plan peer just keeps averaging with its
    own cohort until it fetches the new record. Epoch 0 (operator-pinned
    files, pre-epoch plans) keeps the historical scope strings byte-for-
    byte, so old plan files and old peers interoperate unchanged."""

    mode: str  # "flat" | "hierarchical" | "gossip"
    reason: str
    cliques: List[CliquePlan] = field(default_factory=list)
    median_rtt_s: Optional[float] = None
    epoch: int = 0
    peers: List[str] = field(default_factory=list)  # gossip roster

    @property
    def delegates(self) -> List[str]:
        return [c.delegate for c in self.cliques]

    # ------------------------------------------------------ matchmaking scopes

    def clique_scope(self, clique: CliquePlan) -> str:
        """The matchmaking scope a clique's local rounds form under.
        Epoch-qualified so mixed-version rollouts never cross-join."""
        if self.epoch:
            return f"clique:e{self.epoch}:{clique.key()}"
        return f"clique:{clique.key()}"

    def wan_scope(self) -> str:
        """The matchmaking scope the delegates' WAN round forms under."""
        return f"wan:e{self.epoch}" if self.epoch else "wan"

    def gossip_scope(self, members: Sequence[str]) -> str:
        """The matchmaking scope one gossip neighbor group forms under."""
        key = hashlib.sha256(
            "\x00".join(sorted(members)).encode()
        ).hexdigest()[:12]
        return f"gossip:e{self.epoch}:{key}"

    # ------------------------------------------------------- gossip pairing

    def gossip_groups(self, round_id: str) -> List[List[str]]:
        """Deterministic neighbor groups for one gossip round: the roster is
        permuted by a hash of (epoch, round_id) and chunked into pairs (the
        last group absorbs the odd peer). Every peer holding the same plan
        derives the SAME pairing from the shared round id — no coordination
        message, same trick as ``CliquePlan.key``. Pairings rotate every
        round, so repeated gossip rounds mix the whole swarm."""
        roster = sorted(set(self.peers))
        if len(roster) < 2:
            return [roster] if roster else []
        digest = hashlib.sha256(
            f"{self.epoch}\x00{round_id}".encode()
        ).digest()
        keyed = sorted(
            roster,
            key=lambda p: hashlib.sha256(digest + p.encode()).digest(),
        )
        groups = [
            keyed[i:i + GOSSIP_GROUP_SIZE]
            for i in range(0, len(keyed), GOSSIP_GROUP_SIZE)
        ]
        if len(groups) > 1 and len(groups[-1]) < GOSSIP_GROUP_SIZE:
            groups[-2].extend(groups.pop())
        return [sorted(g) for g in groups]

    def gossip_group_of(self, member_ids, round_id: str) -> Optional[List[str]]:
        """The neighbor group containing this peer (matched by any known
        identity), or None when the peer is not in the gossip roster — the
        runtime then falls back to a flat round with the reason named."""
        ids = [member_ids] if isinstance(member_ids, str) else list(member_ids)
        ids = {str(i) for i in ids if i}
        for group in self.gossip_groups(round_id):
            if ids & set(group):
                return group
        return None

    def assignment(self, member_ids) -> Optional[Assignment]:
        """This peer's assignment, matched by ANY of its known identities
        (a single string or an iterable — endpoint key, telemetry label).
        None for flat plans. A hierarchical plan assigns peers it has
        never seen a direct-WAN singleton, so an unplanned late joiner
        still participates (it rides the WAN round as its own delegate)
        instead of being orphaned."""
        if self.mode != "hierarchical":
            return None
        ids = [member_ids] if isinstance(member_ids, str) else list(member_ids)
        ids = [str(i) for i in ids if i]
        wan_size = len(self.cliques)
        for clique in self.cliques:
            for mid in ids:
                if mid in clique.members:
                    return Assignment(mid, clique, wan_size)
        if not ids:
            return None
        # unplanned peer: direct WAN participant (its own singleton clique)
        me = ids[0]
        return Assignment(
            me, CliquePlan(members=[me], delegate=me), wan_size + 1
        )

    def clique_of(self, member_id: str) -> Optional[int]:
        for i, clique in enumerate(self.cliques):
            if member_id in clique.members:
                return i
        return None

    def same_clique(self, a: str, b: str) -> bool:
        """Whether two peers share a clique — the WAN-vs-local classifier
        the simulator's wire accounting uses."""
        ca, cb = self.clique_of(a), self.clique_of(b)
        return ca is not None and ca == cb

    # ------------------------------------------------------- serialization

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "reason": self.reason,
            "median_rtt_s": self.median_rtt_s,
            "epoch": int(self.epoch),
            "peers": list(self.peers),
            "cliques": [
                {"members": list(c.members), "delegate": c.delegate}
                for c in self.cliques
            ],
        }

    @classmethod
    def from_dict(cls, raw: dict) -> "TopologyPlan":
        cliques = [
            CliquePlan(
                members=[str(m) for m in c.get("members", [])],
                delegate=str(c.get("delegate", "")),
            )
            for c in raw.get("cliques", [])
        ]
        return cls(
            mode=str(raw.get("mode", "flat")),
            reason=str(raw.get("reason", "")),
            cliques=cliques,
            median_rtt_s=raw.get("median_rtt_s"),
            epoch=int(raw.get("epoch", 0) or 0),
            peers=[str(p) for p in raw.get("peers", [])],
        )

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)

    @classmethod
    def load(cls, path: str) -> "TopologyPlan":
        with open(path, "r", encoding="utf-8") as f:
            return cls.from_dict(json.load(f))


def _fresh_links(links, now: Optional[float],
                 stale_after_s: Optional[float]) -> List[dict]:
    """Drop links whose last observation predates the snapshot window.
    A link record without a timestamp passes (folded topology records are
    already the newest fold; only raw event streams carry ``t``)."""
    if now is None or stale_after_s is None or stale_after_s <= 0:
        return list(links)
    horizon = now - stale_after_s
    return [
        l for l in links
        if l.get("t") is None or float(l["t"]) >= horizon
    ]


def plan_topology(
    links: Iterable[dict],
    *,
    client_peers: Sequence[str] = (),
    min_clique_size: int = 2,
    now: Optional[float] = None,
    stale_after_s: Optional[float] = None,
    dst_key: str = "dst",
    instability: Optional[float] = None,
) -> TopologyPlan:
    """Partition the swarm described by ``links`` into a two-level plan.

    ``links``: directed link records (``src``, ``dst_key``, optional
    ``rtt_s``/``goodput_bps``/``peak_bps``/``t``) — the ``--topology``
    fold, a peer's own ``LinkTable.records()``, or the simulator's network
    model. ``client_peers`` are ids that cannot accept inbound connections:
    they are never elected delegate, and with no RTT clique of their own
    they are attached to the fattest listeners (the parameter-server
    degenerate case). ``stale_after_s`` (with ``now``) drops observations
    older than the snapshot window before planning.

    ``instability`` is the caller's churn signal — the fraction of the
    roster lost per recent health fold (``roles/coordinator.py`` derives it
    from ``alive_peers`` deltas). At or above
    ``GOSSIP_INSTABILITY_THRESHOLD`` the planner selects ``mode="gossip"``
    (the paper's remaining degenerate strategy): full-swarm and delegate
    rounds keep dying mid-exchange in such a swarm, so peers average with
    deterministic per-round neighbor pairs instead.

    Falls back to ``mode="flat"`` — never raises — whenever the table is
    too sparse to justify a hierarchy, or when one clique already covers
    every known peer (plain all-reduce is then optimal)."""
    links = _fresh_links(list(links), now, stale_after_s)
    client_set = {str(p) for p in client_peers}
    peers = sorted(
        {l["src"] for l in links if l.get("src")}
        | {l[dst_key] for l in links if l.get(dst_key)}
        | client_set
    )
    if not peers:
        return TopologyPlan("flat", "empty link table")
    if (
        instability is not None
        and instability >= GOSSIP_INSTABILITY_THRESHOLD
        and len(peers) >= 3
    ):
        return TopologyPlan(
            "gossip",
            f"swarm instability {instability * 100.0:.0f}% >= "
            f"{GOSSIP_INSTABILITY_THRESHOLD * 100.0:.0f}% per fold — "
            "gossip neighbor averaging over deterministic per-round pairs",
            peers=peers,
        )
    median_rtt, groups = clique_groups(links, dst_key=dst_key)
    if median_rtt is None:
        return TopologyPlan(
            "flat", "sparse link table (fewer than 2 RTT observations)"
        )
    capacity = uplink_capacity(links, dst_key=dst_key)

    def elect(members: List[str]) -> Optional[str]:
        """Fattest-uplink listener of the clique; None when every member is
        client-mode (such a clique cannot host the WAN leg)."""
        electable = [m for m in members if m not in client_set]
        if not electable:
            return None
        return max(electable, key=lambda m: (capacity.get(m, 0.0), m))

    cliques: List[CliquePlan] = []
    assigned: set = set()
    for members in groups:
        if len(members) < min_clique_size:
            continue
        delegate = elect(sorted(members))
        if delegate is None:
            continue  # all-client clique: members ride the WAN directly
        cliques.append(CliquePlan(sorted(members), delegate))
        assigned.update(members)

    # parameter-server degenerate case: client-mode volunteers that no RTT
    # clique claimed attach to the fattest listeners, round-robin across
    # the fat set so one delegate's uplink is not the whole swarm's funnel
    stray_clients = sorted(client_set - assigned)
    if stray_clients:
        listeners = sorted(
            (p for p in peers if p not in client_set and p not in assigned),
            key=lambda m: (-capacity.get(m, 0.0), m),
        )
        hosts: List[CliquePlan] = list(cliques)
        if listeners:
            rates = sorted(
                (capacity.get(p, 0.0) for p in peers if p not in client_set)
            )
            median_rate = rates[len(rates) // 2] if rates else 0.0
            fat = [
                p for p in listeners
                if capacity.get(p, 0.0) >= FAT_UPLINK_FACTOR * median_rate
                and capacity.get(p, 0.0) > 0.0
            ] or listeners[:1]
            for p in fat:
                server = CliquePlan([p], p)
                cliques.append(server)
                hosts.append(server)
                assigned.add(p)
        if hosts:
            for i, c in enumerate(stray_clients):
                home = hosts[i % len(hosts)]
                home.members = sorted(home.members + [c])
                assigned.add(c)
            for clique in cliques:
                clique.members = sorted(clique.members)

    if not cliques:
        return TopologyPlan(
            "flat", "no low-RTT cliques detected", median_rtt_s=median_rtt
        )
    if len(cliques) == 1 and len(cliques[0].members) >= len(peers):
        return TopologyPlan(
            "flat",
            "single clique covers every peer — plain all-reduce is optimal",
            median_rtt_s=median_rtt,
        )
    covered = sum(len(c.members) for c in cliques)
    return TopologyPlan(
        "hierarchical",
        f"{len(cliques)} cliques cover {covered}/{len(peers)} peers "
        f"(median rtt {median_rtt * 1e3:.1f}ms)",
        cliques=cliques,
        median_rtt_s=median_rtt,
    )


def plan_from_groups(groups: Sequence[Sequence[str]],
                     capacity: Optional[Dict[str, float]] = None,
                     client_peers: Sequence[str] = (),
                     reason: str = "operator-specified cliques",
                     ) -> TopologyPlan:
    """A plan from explicit member groups (operator/spec-driven — e.g. the
    simulator's ``topology.cliques`` key): same election rule, no link
    table needed."""
    capacity = capacity or {}
    client_set = {str(p) for p in client_peers}
    cliques = []
    for members in groups:
        members = sorted(str(m) for m in members)
        if not members:
            continue
        electable = [m for m in members if m not in client_set] or members
        delegate = max(electable, key=lambda m: (capacity.get(m, 0.0), m))
        cliques.append(CliquePlan(members, delegate))
    if len(cliques) < 2:
        return TopologyPlan(
            "flat", "fewer than 2 cliques specified", cliques=[]
        )
    return TopologyPlan("hierarchical", reason, cliques=cliques)
