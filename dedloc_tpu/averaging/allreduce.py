"""Fault-tolerant group all-reduce over TCP: reduce-scatter + all-gather.

The cross-slice replacement for hivemind's butterfly all-reduce
(SURVEY.md §2.6): each group member hosts one bandwidth-weighted span of the
flat vector; senders scatter their spans to the hosts, each host computes the
weighted average of its span, then everyone gathers the reduced spans back.
Weighted by per-peer sample counts so the result is the exact weighted mean
of member vectors.

Roles inside a group (capability parity with the reference):
- normal peer: weight > 0, bandwidth > 0 — sends data AND hosts a span
- auxiliary peer (run_aux.py): weight == 0, bandwidth > 0 — hosts a span,
  contributes bandwidth, sends no data
- client-mode peer (arguments.py:63-65): bandwidth == 0 — sends data and
  pulls results, hosts nothing (outbound connections only)

Weights are arbitrary non-negative floats, not just sample counts: the
collaborative optimizer's contribution ramp scales a freshly-joined peer's
weight from near-zero to its full sample count over its first ramp_rounds
rounds, and its trunk-health gate sends weight 0.0 for a diverged peer —
such a peer rides the aux wire path (zero-weight marker, no data) but still
gathers the group's reduced spans, i.e. it RECEIVES the average it did not
perturb.

Failure contract (mirrors the reference's straggler SLA,
albert/arguments.py:23-28): a SENDER that misses the ``straggler_timeout``
window is simply left out — hosts reduce whatever arrived by then, and all
members still gather identical spans (consistent result, minus the
straggler's contribution). A dead HOST is unrecoverable without redundancy:
its span cannot be gathered, the round raises AllreduceFailed for everyone,
and the group re-forms next round (the reference's 'group failure costs one
round' semantics, contributor notebook cell 3).
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dedloc_tpu import native
from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
)
from dedloc_tpu.averaging.partition import partition_weighted
from dedloc_tpu.dht.protocol import Endpoint, RPCClient, RPCError, RPCServer
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class AllreduceFailed(Exception):
    pass


class _RoundState:
    def __init__(self):
        self.parts: Dict[int, Tuple[np.ndarray, float]] = {}  # sender -> (span, weight)
        self.expected_senders: Optional[set] = None
        self.arrived = asyncio.Event()
        self.reduced: asyncio.Future = asyncio.get_running_loop().create_future()

    def maybe_complete(self) -> None:
        if self.expected_senders is not None and self.expected_senders <= set(
            self.parts
        ):
            self.arrived.set()


class GroupAllReduce:
    """Hosts the RPC handlers and runs rounds. One instance per peer process;
    multiple concurrent rounds are keyed by round_id."""

    def __init__(
        self,
        client: RPCClient,
        server: Optional[RPCServer] = None,
        compression: CompressionType = CompressionType.FLOAT16,
        timeout: float = 30.0,
        straggler_timeout: float = 5.0,
        telemetry_registry=None,  # per-peer scope (telemetry/registry.py)
    ):
        self.client = client
        self.telemetry = telemetry_registry
        self.compression = compression
        self.timeout = timeout
        self.straggler_timeout = straggler_timeout
        self._rounds: Dict[str, _RoundState] = {}
        if server is not None:
            server.register("avg.part", self._rpc_part)
            server.register("avg.get_reduced", self._rpc_get_reduced)

    def _round(self, round_id: str) -> _RoundState:
        if round_id not in self._rounds:
            self._rounds[round_id] = _RoundState()
            # bound handler-created entries too: without this, parts arriving
            # after run()'s cleanup would accumulate forever
            asyncio.get_running_loop().call_later(
                self.timeout * 2, self._rounds.pop, round_id, None
            )
        return self._rounds[round_id]

    # ------------------------------------------------------------- handlers

    async def _rpc_part(self, peer: Endpoint, args) -> dict:
        """A sender delivers its slice of MY span (or a zero-weight marker
        from an auxiliary peer that has no data)."""
        state = self._round(args["round_id"])
        weight = float(args["weight"])
        span = (
            deserialize_array(args["data"]).astype(np.float32)
            if args.get("data") is not None
            else None
        )
        state.parts[int(args["sender"])] = (span, weight)
        state.maybe_complete()
        return {}

    async def _rpc_get_reduced(self, peer: Endpoint, args) -> dict:
        """A member pulls my reduced span (awaits until reduction done)."""
        state = self._round(args["round_id"])
        data, weight = await asyncio.wait_for(
            asyncio.shield(state.reduced), timeout=self.timeout
        )
        return {
            "data": serialize_array(data, self.compression, checksum=True),
            "weight": weight,
        }

    # ------------------------------------------------------------------ run

    async def run(
        self,
        round_id: str,
        my_index: int,
        vector: np.ndarray,
        weight: float,
        endpoints: Sequence[Optional[Endpoint]],
        bandwidths: Sequence[float],
    ) -> np.ndarray:
        """Run one round. ``endpoints[i] is None`` marks a client-mode member
        (it hosts nothing); my own endpoint entry is ignored. Returns the
        weighted average vector (same shape as input).
        """
        n = len(endpoints)
        assert 0 <= my_index < n
        can_host = [ep is not None for ep in endpoints]
        if not any(can_host):
            raise AllreduceFailed(f"round {round_id}: no member can host a span")
        spans = partition_weighted(len(vector), list(bandwidths), can_host)
        # every member announces itself to every host — auxiliary peers send a
        # zero-weight marker instead of data, so hosts know not to wait
        senders = set(range(n))

        my_state = None
        lo, hi = spans[my_index]
        hosts_span = hi > lo
        if hosts_span:
            my_state = self._round(round_id)
            my_state.expected_senders = set(senders)
            my_state.maybe_complete()

        tele = telemetry.resolve(self.telemetry)
        span_cm = (
            tele.span("allreduce.round", round_id=round_id, group_size=n)
            if tele is not None
            else telemetry.null_span()
        )
        try:
            with span_cm as ctx:
                try:
                    result = await asyncio.wait_for(
                        self._run_inner(
                            round_id, my_index, vector, weight, endpoints,
                            spans, my_state, senders,
                        ),
                        timeout=self.timeout,
                    )
                except (
                    asyncio.TimeoutError, ConnectionError, OSError, RPCError,
                    ValueError,
                ) as e:
                    # RPCError covers remote-side failures (a host whose
                    # handler timed out or crashed replies ok=False);
                    # ValueError covers corrupt frames (checksum/shape
                    # mismatch) — a failed round must cost one round, not the
                    # training process
                    if tele is not None:
                        tele.counter("allreduce.failures").inc()
                        ctx["ok"] = False
                        ctx["error"] = type(e).__name__
                    raise AllreduceFailed(f"round {round_id}: {e!r}") from e
                if tele is not None:
                    tele.counter("allreduce.rounds").inc()
                    ctx["ok"] = True
                    ctx["bytes"] = int(vector.nbytes)
                return result
        finally:
            # deferred cleanup: slower members may still pull our reduced span
            asyncio.get_running_loop().call_later(
                self.timeout, self._rounds.pop, round_id, None
            )

    async def _run_inner(
        self, round_id, my_index, vector, weight, endpoints, spans, my_state,
        senders,
    ) -> np.ndarray:
        n = len(endpoints)
        tele = telemetry.resolve(self.telemetry)
        # 1) scatter: send my slice of each host's span (zero-weight marker
        # when I have no data, so hosts never wait on an aux peer)
        sends = []
        for j in range(n):
            lo, hi = spans[j]
            if hi <= lo:
                continue  # client-mode host: nothing to send
            if j == my_index:
                my_state.parts[my_index] = (
                    vector[lo:hi].astype(np.float32) if weight > 0 else None,
                    weight if weight > 0 else 0.0,
                )
                my_state.maybe_complete()
                continue
            payload = {
                "round_id": round_id,
                "sender": my_index,
                "weight": weight if weight > 0 else 0.0,
                "data": (
                    serialize_array(vector[lo:hi], self.compression, checksum=True)
                    if weight > 0
                    else None
                ),
            }
            if tele is not None and weight > 0:
                # logical tensor bytes moved (pre-compression float32); the
                # wire view lives in the frame-level net.bytes_* counters
                tele.counter("allreduce.bytes_sent").inc((hi - lo) * 4)
            sends.append(
                self.client.call(
                    endpoints[j], "avg.part", payload, timeout=self.timeout
                )
            )
        await asyncio.gather(*sends)

        # 2) reduce my span once all expected parts arrive — or after the
        # straggler window closes (arguments.py:23-28 semantics): reduce what
        # we have; the missing sender simply doesn't contribute this round
        if my_state is not None:
            try:
                await asyncio.wait_for(
                    my_state.arrived.wait(), timeout=self.straggler_timeout
                )
            except asyncio.TimeoutError:
                missing = (my_state.expected_senders or set()) - set(my_state.parts)
                logger.warning(
                    f"{round_id}: proceeding without stragglers {sorted(missing)}"
                )
                if tele is not None:
                    tele.counter("allreduce.stragglers").inc(len(missing))
                    tele.event(
                        "allreduce.stragglers", round_id=round_id,
                        missing=sorted(missing),
                    )
            total_w = sum(w for p, w in my_state.parts.values() if p is not None)
            lo, hi = spans[my_index]
            if total_w > 0:
                acc = np.zeros(hi - lo, np.float32)
                for part, w in my_state.parts.values():
                    if part is not None and w > 0:
                        native.axpy(acc, part, w)  # acc += w * part, in C++
                reduced = native.scale(acc, 1.0 / total_w)
            else:  # all-aux group: nothing to average
                reduced = vector[lo:hi].astype(np.float32)
            if not my_state.reduced.done():
                my_state.reduced.set_result((reduced, total_w))

        # 3) gather all reduced spans
        async def fetch(j: int) -> np.ndarray:
            lo, hi = spans[j]
            if hi <= lo:
                return np.zeros(0, np.float32)
            if j == my_index:
                return (await my_state.reduced)[0]
            reply = await self.client.call(
                endpoints[j],
                "avg.get_reduced",
                {"round_id": round_id},
                timeout=self.timeout,
            )
            if tele is not None:
                tele.counter("allreduce.bytes_received").inc((hi - lo) * 4)
            return deserialize_array(reply["data"]).astype(np.float32)

        pieces = await asyncio.gather(*(fetch(j) for j in range(n)))
        out = np.concatenate(pieces)
        assert out.size == vector.size
        return out
