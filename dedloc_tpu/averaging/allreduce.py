"""Fault-tolerant group all-reduce over TCP: pipelined reduce-scatter +
all-gather with per-chunk streaming.

The cross-slice replacement for hivemind's butterfly all-reduce
(SURVEY.md §2.6): each group member hosts one bandwidth-weighted span of the
flat vector; senders scatter their spans to the hosts, each host computes the
weighted average of its span, then everyone gathers the reduced spans back.
Weighted by per-peer sample counts so the result is the exact weighted mean
of member vectors.

Wire-path pipelining (the hivemind part-streaming capability, TPU-native):
each span is split into fixed-size chunks (``chunk_size`` elements;
``chunk_size <= 0`` restores the monolithic-span wire format). Three things
overlap within one round instead of running back-to-back:

- hosts REDUCE each chunk eagerly, the moment the last expected sender's
  copy of that chunk arrives — reduction overlaps the remaining transfers;
- the all-gather STREAMS: every member requests all chunks up front and each
  request completes the instant that chunk finishes reducing, so reduced
  chunks ride back over the wire while later chunks are still inbound;
- a sender's scatter is per-chunk, so a host never waits for a full
  monolithic span before starting work.

Roles inside a group (capability parity with the reference):
- normal peer: weight > 0, bandwidth > 0 — sends data AND hosts a span
- auxiliary peer (run_aux.py): weight == 0, bandwidth > 0 — hosts a span,
  contributes bandwidth, sends no data (ONE zero-weight marker per host
  covers every chunk)
- client-mode peer (arguments.py:63-65): bandwidth == 0 — sends data and
  pulls results, hosts nothing (outbound connections only)

Weights are arbitrary non-negative floats, not just sample counts: the
collaborative optimizer's contribution ramp scales a freshly-joined peer's
weight from near-zero to its full sample count over its first ramp_rounds
rounds, and its trunk-health gate sends weight 0.0 for a diverged peer —
such a peer rides the aux wire path (zero-weight marker, no data) but still
gathers the group's reduced spans, i.e. it RECEIVES the average it did not
perturb.

Failure contract (mirrors the reference's straggler SLA,
albert/arguments.py:23-28): a SENDER that misses the ``straggler_timeout``
window is simply left out — hosts finalize whatever chunks arrived by then,
and all members still gather identical spans (consistent result, minus the
straggler's contribution; each chunk is served from exactly one host, so
every member sees the same bytes). A dead HOST is unrecoverable without
redundancy: its span cannot be gathered, the round raises AllreduceFailed
for everyone, and the group re-forms next round (the reference's 'group
failure costs one round' semantics, contributor notebook cell 3).
"""
from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from dedloc_tpu import native
from dedloc_tpu.core.serialization import (
    CompressionType,
    deserialize_array,
    serialize_array,
    wire_roundtrip,
)
from dedloc_tpu.averaging.partition import partition_weighted
from dedloc_tpu.dht.protocol import Endpoint, RPCClient, RPCError, RPCServer
from dedloc_tpu.telemetry import registry as telemetry
from dedloc_tpu.telemetry.links import endpoint_key
from dedloc_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# default chunk: 128Ki fp32 elements = 512 KiB raw per message — small enough
# that several chunks are in flight per span on real models, large enough
# that framing/syscall overhead stays negligible
DEFAULT_CHUNK_SIZE = 131072


class AllreduceFailed(Exception):
    pass


def span_chunks(
    lo: int, hi: int, chunk_size: int
) -> List[Tuple[int, int]]:
    """Absolute [lo, hi) bounds of each chunk of one span. ``chunk_size <= 0``
    means no chunking (one chunk per span — the monolithic wire format).
    Every member derives the identical chunking from the identical spans."""
    if hi <= lo:
        return []
    if chunk_size <= 0:
        return [(lo, hi)]
    return [
        (c, min(c + chunk_size, hi)) for c in range(lo, hi, chunk_size)
    ]


class _ChunkState:
    """One chunk of MY span: eagerly-accumulated weighted sum + the set of
    senders whose copy arrived. ``done`` resolves to the reduced fp32 chunk
    the moment the last expected sender delivers (or the straggler window
    closes); ``wire`` caches the serialized reply so n-1 gatherers cost one
    encode."""

    __slots__ = ("acc", "weight", "arrived", "done", "wire")

    def __init__(self):
        self.acc: Optional[np.ndarray] = None
        self.weight = 0.0
        self.arrived: Set[int] = set()
        self.done: asyncio.Future = (
            asyncio.get_running_loop().create_future()
        )
        self.wire: Optional[bytes] = None


class _RoundState:
    def __init__(self):
        self.chunks: Dict[int, _ChunkState] = {}
        # set by run() on the hosting member; handlers may buffer parts that
        # arrive first, but no chunk finalizes until these exist
        self.expected_senders: Optional[Set[int]] = None
        self.chunk_bounds: Optional[List[Tuple[int, int]]] = None
        self.local_span: Optional[np.ndarray] = None  # my fp32 span slice
        self.span_lo = 0
        self.reduce_s = 0.0  # CPU seconds spent in axpy/scale on this host
        # hierarchical averaging (averaging/topology.py): a clique-level
        # round runs in SUM mode — finalize serves the raw weighted sum
        # (and its total weight) instead of the mean, so the clique's
        # delegate can carry the weight-summed contribution into the WAN
        # round without a divide/re-multiply that would change the math.
        # Set by run() before expected_senders, so no chunk can finalize
        # under the wrong mode.
        self.normalize = True

    def chunk(self, c: int) -> _ChunkState:
        if c not in self.chunks:
            self.chunks[c] = _ChunkState()
        return self.chunks[c]

    @property
    def dataless(self) -> Set[int]:
        """Senders whose zero-weight marker (chunk == -1) covers all chunks."""
        marker = self.chunks.get(-1)
        return marker.arrived if marker is not None else set()

    def accumulate(
        self, c: int, part: np.ndarray, weight: float, own: bool = False,
        norm: Optional[float] = None,
    ) -> None:
        """Fold one sender's copy of chunk ``c`` into the eager accumulator.
        ``own=True`` marks a freshly-deserialized array the state may mutate
        in place; local slices (possibly views of the caller's reused flat
        buffer) are copied first. ``norm`` is the sender's NORMALIZATION
        weight when it differs from its axpy scale: a hierarchical delegate
        delivers its clique's pre-summed vector with ``weight=1`` (the sum
        must not be re-scaled) but ``norm=W_clique`` (the denominator must
        count every clique member it already folded in)."""
        st = self.chunk(c)
        t0 = telemetry.monotonic_clock()
        if st.acc is None:
            if own and part.dtype == np.float32 and part.flags["C_CONTIGUOUS"]:
                st.acc = part
            else:
                st.acc = np.array(part, dtype=np.float32)
            native.scale(st.acc, weight)
        else:
            native.axpy(st.acc, part, weight)
        self.reduce_s += telemetry.monotonic_clock() - t0
        st.weight += weight if norm is None else norm

    def maybe_finalize(self, c: int) -> None:
        """Resolve chunk ``c`` if every expected sender delivered it (data,
        or the round-wide zero-weight marker)."""
        if self.expected_senders is None or c < 0:
            return
        st = self.chunks.get(c)
        if st is None or st.done.done():
            return
        if self.expected_senders <= (st.arrived | self.dataless):
            self.finalize(c)

    def finalize(self, c: int) -> None:
        """Resolve chunk ``c`` with whatever arrived (straggler finalize
        path included). Requires run() to have initialized the round."""
        st = self.chunk(c)
        if st.done.done():
            return
        if not self.normalize:
            # SUM mode (hierarchical clique round): serve the raw weighted
            # sum — an empty accumulator is a legitimate zero sum (an
            # all-aux/all-gated clique), not a fallback to local data
            if st.acc is None:
                lo, hi = self.chunk_bounds[c]
                st.acc = np.zeros(hi - lo, dtype=np.float32)
            st.done.set_result(st.acc)
            return
        if st.weight > 0:
            t0 = telemetry.monotonic_clock()
            reduced = native.scale(st.acc, 1.0 / st.weight)
            self.reduce_s += telemetry.monotonic_clock() - t0
        else:
            # all-aux group: nothing to average; serve my own slice (copied —
            # local_span may view a flat buffer the caller reuses next round,
            # and slow members pull chunks after this round returns)
            lo, hi = self.chunk_bounds[c]
            reduced = np.array(
                self.local_span[lo - self.span_lo : hi - self.span_lo],
                dtype=np.float32,
            )
        st.done.set_result(reduced)

    def maybe_finalize_all(self) -> None:
        if self.expected_senders is None or self.chunk_bounds is None:
            return
        for c in range(len(self.chunk_bounds)):
            self.maybe_finalize(c)

    def finalize_all(self) -> None:
        for c in range(len(self.chunk_bounds)):
            self.finalize(c)

    def missing_senders(self) -> Set[int]:
        """Expected senders that did not deliver every chunk of my span."""
        if self.expected_senders is None or self.chunk_bounds is None:
            return set()
        missing: Set[int] = set()
        covered = self.dataless
        for c in range(len(self.chunk_bounds)):
            st = self.chunks.get(c)
            arrived = st.arrived if st is not None else set()
            missing |= self.expected_senders - (arrived | covered)
        return missing


class GroupAllReduce:
    """Hosts the RPC handlers and runs rounds. One instance per peer process;
    multiple concurrent rounds are keyed by round_id."""

    def __init__(
        self,
        client: RPCClient,
        server: Optional[RPCServer] = None,
        compression: CompressionType = CompressionType.FLOAT16,
        timeout: float = 30.0,
        straggler_timeout: float = 5.0,
        chunk_size: int = DEFAULT_CHUNK_SIZE,  # elements per wire chunk;
        # <= 0 disables chunking (one monolithic message per span)
        telemetry_registry=None,  # per-peer scope (telemetry/registry.py)
    ):
        self.client = client
        self.telemetry = telemetry_registry
        self.compression = compression
        self.timeout = timeout
        self.straggler_timeout = straggler_timeout
        self.chunk_size = int(chunk_size)
        self._rounds: Dict[str, _RoundState] = {}
        if server is not None:
            server.register("avg.part", self._rpc_part)
            server.register("avg.get_reduced", self._rpc_get_reduced)

    def _round(self, round_id: str) -> _RoundState:
        if round_id not in self._rounds:
            self._rounds[round_id] = _RoundState()
            # bound handler-created entries too: without this, parts arriving
            # after run()'s cleanup would accumulate forever
            asyncio.get_running_loop().call_later(
                self.timeout * 2, self._rounds.pop, round_id, None
            )
        return self._rounds[round_id]

    # ------------------------------------------------------------- handlers

    async def _rpc_part(self, peer: Endpoint, args) -> dict:
        """A sender delivers one chunk of MY span (``chunk == -1``: a
        zero-weight marker from an auxiliary peer with no data, covering
        every chunk of the round)."""
        state = self._round(args["round_id"])
        sender = int(args["sender"])
        weight = float(args["weight"])
        # a hierarchical delegate's normalization weight (its clique's
        # summed weight) rides next to its axpy scale; plain senders omit
        # the field and the two coincide
        norm = float(args.get("norm", weight))
        c = int(args.get("chunk", 0))
        data = args.get("data")
        if data is None or c < 0:
            # round-wide marker: this sender contributes nothing, ever
            state.chunk(-1).arrived.add(sender)
            state.maybe_finalize_all()
            return {}
        st = state.chunk(c)
        if sender in st.arrived or sender in state.dataless:
            return {}  # duplicate delivery must not double-accumulate
        if st.done.done():
            # a straggler's part landing AFTER the window finalized this
            # chunk: the finalized mean (scaled in place, possibly already
            # served to gatherers) must never be mutated again — the late
            # sender simply missed this round, per the straggler SLA
            return {}
        part = deserialize_array(data)
        if weight > 0:
            state.accumulate(c, part, weight, own=True, norm=norm)
        st.arrived.add(sender)
        state.maybe_finalize(c)
        return {}

    async def _rpc_get_reduced(self, peer: Endpoint, args) -> dict:
        """A member pulls one reduced chunk of my span (awaits until that
        chunk finishes reducing — the streaming all-gather). The reply
        carries the chunk's accumulated weight: sum-mode gatherers (the
        hierarchical clique round) need the denominator their delegate
        will advertise in the WAN round; mean-mode callers ignore it."""
        state = self._round(args["round_id"])
        st = state.chunk(int(args.get("chunk", 0)))
        data = await asyncio.wait_for(
            asyncio.shield(st.done), timeout=self.timeout
        )
        if st.wire is None:  # encode once, serve n-1 gatherers from cache
            st.wire = serialize_array(data, self.compression, checksum=True)
        return {"data": st.wire, "weight": st.weight}

    # ------------------------------------------------------------------ run

    async def run(
        self,
        round_id: str,
        my_index: int,
        vector: np.ndarray,
        weight: float,
        endpoints: Sequence[Optional[Endpoint]],
        bandwidths: Sequence[float],
        chunk_size: Optional[int] = None,
        norm_weight: Optional[float] = None,
        normalize: bool = True,
    ):
        """Run one round. ``endpoints[i] is None`` marks a client-mode member
        (it hosts nothing); my own endpoint entry is ignored. Returns the
        weighted average vector (same shape as input) in a freshly allocated
        buffer — the result ESCAPES the round (callers hold it across rounds,
        e.g. an overlapped optimizer boundary), so it cannot alias a reused
        scratch buffer.

        ``chunk_size`` overrides this instance's default for ONE round —
        the averager passes the group-negotiated value here, since chunk
        indices only mean the same thing when every member splits the
        identical spans with the identical chunk size.

        Hierarchical (two-level) averaging hooks (averaging/topology.py):

        - ``norm_weight`` decouples this member's NORMALIZATION weight from
          its axpy scale ``weight`` — a clique delegate contributes its
          clique's pre-summed vector with ``weight=1.0`` and
          ``norm_weight=W_clique``, so the WAN mean divides by every
          gradient the sum already carries without re-scaling the sum.
        - ``normalize=False`` runs the round in SUM mode: hosts serve the
          raw weighted sum and the return value becomes the tuple
          ``(summed_vector, total_weight)`` — the contribution a delegate
          carries up. The round FAILS (AllreduceFailed) when chunks
          finalized with different total weights (a straggler was dropped
          from part of the span): a delegate must never advertise a
          denominator its sum does not actually carry.
        """
        n = len(endpoints)
        assert 0 <= my_index < n
        chunk_size = (
            self.chunk_size if chunk_size is None else int(chunk_size)
        )
        can_host = [ep is not None for ep in endpoints]
        if not any(can_host):
            raise AllreduceFailed(f"round {round_id}: no member can host a span")
        spans = partition_weighted(len(vector), list(bandwidths), can_host)
        # every member announces itself to every host — auxiliary peers send a
        # zero-weight marker instead of data, so hosts know not to wait
        senders = set(range(n))

        my_state = None
        lo, hi = spans[my_index]
        hosts_span = hi > lo
        if hosts_span:
            my_state = self._round(round_id)
            my_state.normalize = normalize  # before expected_senders: no
            # chunk may finalize under the wrong mode
            my_state.expected_senders = set(senders)
            my_state.chunk_bounds = span_chunks(lo, hi, chunk_size)
            my_state.span_lo = lo
            my_state.local_span = np.ascontiguousarray(
                vector[lo:hi], dtype=np.float32
            )
            for c in range(len(my_state.chunk_bounds)):
                # pre-create every chunk state: maybe_finalize skips chunks
                # it has never seen, so an all-dataless round whose markers
                # all landed BEFORE run() would otherwise finalize nothing
                # eagerly and idle out the full straggler window
                my_state.chunk(c)
            my_state.maybe_finalize_all()

        tele = telemetry.resolve(self.telemetry)
        span_cm = (
            # trace_seed: every member derives the round's trace id from the
            # shared round_id, so per-peer traces stitch even without an
            # enclosing avg.round span (bare GroupAllReduce harnesses)
            tele.span(
                "allreduce.round", trace_seed=round_id, round_id=round_id,
                group_size=n,
            )
            if tele is not None
            else telemetry.null_span()
        )
        try:
            with span_cm as ctx:
                try:
                    result = await asyncio.wait_for(
                        self._run_inner(
                            round_id, my_index, vector, weight, endpoints,
                            spans, my_state, senders, ctx, chunk_size,
                            norm_weight, normalize,
                        ),
                        timeout=self.timeout,
                    )
                except (
                    asyncio.TimeoutError, ConnectionError, OSError, RPCError,
                    ValueError,
                ) as e:
                    # RPCError covers remote-side failures (a host whose
                    # handler timed out or crashed replies ok=False);
                    # ValueError covers corrupt frames (checksum/shape
                    # mismatch) — a failed round must cost one round, not the
                    # training process
                    if tele is not None:
                        tele.counter("allreduce.failures").inc()
                        ctx["ok"] = False
                        ctx["error"] = type(e).__name__
                    raise AllreduceFailed(f"round {round_id}: {e!r}") from e
                if tele is not None:
                    tele.counter("allreduce.rounds").inc()
                    ctx["ok"] = True
                    ctx["bytes"] = int(vector.nbytes)
                    if my_state is not None:
                        ctx["reduce_s"] = round(my_state.reduce_s, 6)
                return result
        finally:
            # deferred cleanup: slower members may still pull our reduced span
            asyncio.get_running_loop().call_later(
                self.timeout, self._rounds.pop, round_id, None
            )

    async def _run_inner(
        self, round_id, my_index, vector, weight, endpoints, spans, my_state,
        senders, ctx, chunk_size, norm_weight=None, normalize=True,
    ):
        n = len(endpoints)
        norm = weight if norm_weight is None else float(norm_weight)
        # sum-mode bookkeeping: every gathered chunk's total weight — the
        # delegate's denominator, and the uniformity check's evidence
        chunk_weights: List[float] = []
        tele = telemetry.resolve(self.telemetry)
        # per-destination wire accounting for THIS round: folded into the
        # link estimator (telemetry/links.py) per chunk, and emitted as one
        # allreduce.link event per remote host at round end — the per-hop
        # rows the --trace timeline and the --topology matrix are built from
        link_acc: Dict[int, Dict[str, float]] = {}

        def _acc(j: int) -> Dict[str, float]:
            if j not in link_acc:
                link_acc[j] = {
                    "sent_bytes": 0.0, "recv_bytes": 0.0, "chunks_sent": 0.0,
                    "chunks_recv": 0.0, "send_s": 0.0, "wait_s": 0.0,
                    "max_chunk_s": 0.0,
                }
            return link_acc[j]

        out = np.empty(len(vector), np.float32)
        # one chunk-bounds derivation per host, shared by the gather loop,
        # the scatter build and the telemetry count below — these MUST agree
        # (chunk indices are protocol state)
        chunks_by_host = [
            span_chunks(jlo, jhi, chunk_size) if jhi > jlo else []
            for jlo, jhi in spans
        ]

        # the streaming all-gather is launched FIRST: every chunk request
        # parks at its host and completes the moment that chunk reduces, so
        # reduced chunks flow back while later chunks are still being
        # scattered/reduced — this is where the pipeline wins its wall-clock
        gather_start = telemetry.monotonic_clock()

        async def fetch_chunk(j: int, c: int, clo: int, chi: int) -> None:
            t0 = telemetry.monotonic_clock()
            reply = await self.client.call(
                endpoints[j],
                "avg.get_reduced",
                {"round_id": round_id, "chunk": c},
                timeout=self.timeout,
            )
            data = deserialize_array(reply["data"])
            if data.size != chi - clo:
                raise ValueError(
                    f"chunk size mismatch: got {data.size}, want {chi - clo}"
                )
            np.copyto(out[clo:chi], data.reshape(-1), casting="unsafe")
            if not normalize:
                chunk_weights.append(float(reply.get("weight", 0.0)))
            if tele is not None:
                raw = (chi - clo) * 4
                dt = telemetry.monotonic_clock() - t0
                wire = len(reply["data"])
                tele.counter("allreduce.bytes_received").inc(raw)
                tele.counter("allreduce.chunks_received").inc()
                tele.counter("avg.bytes_saved").inc(max(0, raw - wire))
                tele.histogram("allreduce.chunk_latency_s").observe(dt)
                # NOT fed into the LinkTable: this wall includes the host's
                # reduce/straggler park (the request waits for the chunk to
                # finalize), which would blame a stalled SENDER's delay on
                # the innocent host's link — the persistent per-link
                # estimator only eats pure wire timings (the scatter path);
                # the round-scoped wait still lands on the allreduce.link
                # event below, where --trace reads it WITH the straggler
                # events that explain it
                acc = _acc(j)
                acc["recv_bytes"] += wire
                acc["chunks_recv"] += 1
                acc["wait_s"] += dt
                acc["max_chunk_s"] = max(acc["max_chunk_s"], dt)

        async def fetch_own(c: int, clo: int, chi: int) -> None:
            data = await asyncio.shield(my_state.chunk(c).done)
            if not normalize:
                chunk_weights.append(float(my_state.chunk(c).weight))
            if self.compression is not CompressionType.NONE:
                # adopt my own span THROUGH the wire codec: every other
                # member decodes the lossy wire bytes, and synchronous-SGD
                # emulation wants all replicas to apply bit-identical
                # values — a host keeping its fp32 low bits would drift
                # its params from the rest of the group every round
                data = wire_roundtrip(data, self.compression)
            np.copyto(out[clo:chi], data, casting="unsafe")

        gathers = []
        for j in range(n):
            chunks = chunks_by_host[j]
            if not chunks:
                continue
            if j == my_index:
                gathers.extend(
                    fetch_own(c, clo, chi)
                    for c, (clo, chi) in enumerate(chunks)
                )
            else:
                gathers.extend(
                    fetch_chunk(j, c, clo, chi)
                    for c, (clo, chi) in enumerate(chunks)
                )
        gather_task = asyncio.ensure_future(
            asyncio.gather(*gathers)
        )

        try:
            # scatter: send my slice of each host's span, chunk by chunk
            # (zero-weight marker when I have no data, so hosts never wait
            # on an aux peer). Remote sends are interleaved CHUNK-MAJOR —
            # every host's chunk 0 before any host's chunk 1 — so each host
            # can start reducing (and serving) its first chunks while the
            # rest of the scatter is still on the wire; host-major order
            # would starve the last host until the whole span drained.
            per_host: List[List[Tuple[int, int, int, int]]] = []  # (j, c, lo, hi)
            sends = []
            for j in range(n):
                jlo, jhi = spans[j]
                if jhi <= jlo:
                    continue  # client-mode host: nothing to send
                if j == my_index:
                    # self-delivery skips the RPC but NOT the codec: my own
                    # contribution must suffer the identical quantization as
                    # the copies other hosts receive, or (a) my hosted span
                    # would mix full-precision self bits that no other
                    # replica path models, and (b) the optimizer's error
                    # feedback — which assumes EVERY contributed element was
                    # wire-compressed — would re-inject a residual that was
                    # never actually lost for my own span, a same-sign
                    # drift added every round
                    if weight > 0:
                        for c, (clo, chi) in enumerate(my_state.chunk_bounds):
                            part = my_state.local_span[clo - jlo : chi - jlo]
                            lossy = (
                                self.compression is not CompressionType.NONE
                            )
                            if lossy:
                                part = wire_roundtrip(part, self.compression)
                            # the roundtripped array is fresh (never a view
                            # of local_span), so the accumulator may adopt
                            # and scale it in place instead of copying again
                            my_state.accumulate(
                                c, part, weight, own=lossy, norm=norm
                            )
                            my_state.chunk(c).arrived.add(my_index)
                    else:
                        my_state.chunk(-1).arrived.add(my_index)
                    my_state.maybe_finalize_all()
                    continue
                if weight <= 0:
                    sends.append(
                        self.client.call(
                            endpoints[j], "avg.part",
                            {
                                "round_id": round_id, "sender": my_index,
                                "weight": 0.0, "chunk": -1, "data": None,
                            },
                            timeout=self.timeout,
                        )
                    )
                    continue
                per_host.append([
                    (j, c, clo, chi)
                    for c, (clo, chi) in enumerate(chunks_by_host[j])
                ])
            async def send_chunk(j: int, c: int, clo: int, chi: int) -> None:
                # encode INSIDE the send coroutine: each chunk's codec work
                # is followed by a yield into the RPC await, so inbound
                # parts keep reducing and the gather keeps draining between
                # encodes — serializing the whole vector up front would
                # block the loop for the full codec latency and hold every
                # compressed payload in memory at once
                payload = serialize_array(
                    vector[clo:chi], self.compression, checksum=True
                )
                if tele is not None:
                    raw = (chi - clo) * 4
                    # logical tensor bytes moved (pre-compression fp32);
                    # the frame-level wire view lives in net.bytes_*
                    tele.counter("allreduce.bytes_sent").inc(raw)
                    tele.counter("allreduce.chunks_sent").inc()
                    tele.counter("avg.bytes_saved").inc(
                        max(0, raw - len(payload))
                    )
                part_args = {
                    "round_id": round_id, "sender": my_index,
                    "weight": weight, "chunk": c, "data": payload,
                }
                if norm != weight:
                    # hierarchical delegate: axpy scale 1.0, denominator
                    # W_clique — plain senders keep the smaller frame
                    part_args["norm"] = norm
                t0 = telemetry.monotonic_clock()
                await self.client.call(
                    endpoints[j], "avg.part", part_args,
                    timeout=self.timeout,
                )
                if tele is not None:
                    dt = telemetry.monotonic_clock() - t0
                    tele.links().observe_transfer(
                        endpoints[j], len(payload), dt
                    )
                    acc = _acc(j)
                    acc["sent_bytes"] += len(payload)
                    acc["chunks_sent"] += 1
                    acc["send_s"] += dt
                    acc["max_chunk_s"] = max(acc["max_chunk_s"], dt)

            for row in range(max((len(h) for h in per_host), default=0)):
                for host_chunks in per_host:
                    if row >= len(host_chunks):
                        continue
                    j, c, clo, chi = host_chunks[row]
                    sends.append(send_chunk(j, c, clo, chi))
            await asyncio.gather(*sends)

            # straggler window (arguments.py:23-28 semantics): once my own
            # sends are out, give the remaining senders ``straggler_timeout``
            # to deliver my span's chunks, then finalize with what arrived —
            # a missing sender simply doesn't contribute this round
            if my_state is not None:
                pending = [
                    my_state.chunk(c).done
                    for c in range(len(my_state.chunk_bounds))
                ]
                try:
                    if pending:
                        await asyncio.wait_for(
                            asyncio.shield(asyncio.gather(*pending)),
                            timeout=self.straggler_timeout,
                        )
                except asyncio.TimeoutError:
                    missing = my_state.missing_senders()
                    logger.warning(
                        f"{round_id}: proceeding without stragglers "
                        f"{sorted(missing)}"
                    )
                    if tele is not None:
                        tele.counter("allreduce.stragglers").inc(len(missing))
                        tele.event(
                            "allreduce.stragglers", round_id=round_id,
                            missing=sorted(missing),
                        )
                    my_state.finalize_all()

            await gather_task
        except BaseException:
            gather_task.cancel()
            raise
        if ctx is not None and isinstance(ctx, dict):
            ctx["gather_wait_s"] = round(
                telemetry.monotonic_clock() - gather_start, 6
            )
            ctx["chunks"] = sum(len(c) for c in chunks_by_host)
        if tele is not None:
            # one allreduce.link event per remote hop of this round: which
            # link each byte crossed, how long this member waited on it —
            # the rows --trace attributes a stall with, and (with link.stats)
            # the per-link input --topology ranks links by
            for j in sorted(link_acc):
                acc = link_acc[j]
                tele.event(
                    "allreduce.link", round_id=round_id,
                    dst=endpoint_key(endpoints[j]),
                    sent_bytes=int(acc["sent_bytes"]),
                    recv_bytes=int(acc["recv_bytes"]),
                    chunks_sent=int(acc["chunks_sent"]),
                    chunks_recv=int(acc["chunks_recv"]),
                    send_s=round(acc["send_s"], 6),
                    wait_s=round(acc["wait_s"], 6),
                    max_chunk_s=round(acc["max_chunk_s"], 6),
                )
        if not normalize:
            # SUM mode: the vector is only a valid clique contribution if
            # every chunk's sum carries the SAME set of members — a chunk
            # finalized short (straggler dropped mid-span) would make the
            # delegate advertise a denominator its sum does not carry
            if not chunk_weights:
                raise AllreduceFailed(
                    f"round {round_id}: sum mode gathered no chunks"
                )
            w0 = chunk_weights[0]
            if any(abs(w - w0) > 1e-6 * max(1.0, abs(w0))
                   for w in chunk_weights):
                raise AllreduceFailed(
                    f"round {round_id}: non-uniform chunk weights "
                    f"{sorted(set(round(w, 9) for w in chunk_weights))} — "
                    f"a straggler was dropped from part of the span"
                )
            return out, w0
        return out
