"""Linear evaluation of a self-supervised trunk (SwAV quality anchor).

Capability parity with the reference's evaluation protocol for SwAV
checkpoints: extract features from the frozen ResNet trunk (vissl
``extract_main``, swav/vissl/vissl/engines/extract.py) and train a linear
classifier on them, scoring top-1/top-5 accuracy (vissl meters,
swav/vissl/vissl/meters/; quality anchors in swav/vissl/MODEL_ZOO.md:191-196
are ImageNet-1K linear top-1 numbers). The trunk weights come from a SwAV
collaborative checkpoint via ``init_model_from_weights``-style surgery
(vissl/utils/checkpoint.py:373 capability): only the ``trunk`` subtree is
consumed; heads are discarded.

TPU shape: feature extraction is one jitted eval forward over static-shape
batches; the probe is a jitted softmax regression on cached features (the
standard protocol trains the linear layer only, so there is no need to
re-run the trunk per epoch).
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class LinearProbeArguments:
    learning_rate: float = 0.01
    momentum: float = 0.9
    weight_decay: float = 1e-6
    num_epochs: int = 10
    batch_size: int = 64
    seed: int = 0


class TopKMeter:
    """Streaming top-k accuracy meter (vissl AccuracyListMeter capability)."""

    def __init__(self, ks: Tuple[int, ...] = (1, 5)):
        self.ks = ks
        self.correct = {k: 0 for k in ks}
        self.total = 0

    def update(self, logits: np.ndarray, labels: np.ndarray) -> None:
        order = np.argsort(-logits, axis=-1)
        for k in self.ks:
            topk = order[:, :k]
            self.correct[k] += int((topk == labels[:, None]).any(axis=1).sum())
        self.total += len(labels)

    def value(self) -> Dict[str, float]:
        return {
            f"top_{k}": self.correct[k] / max(1, self.total) for k in self.ks
        }


def extract_features(
    trunk_apply,
    images: np.ndarray,  # [N, H, W, C]
    batch_size: int = 64,
) -> np.ndarray:
    """Frozen-trunk feature extraction over static-shape batches
    (extract_main capability). ``trunk_apply(images) -> [B, D]`` must be the
    eval-mode trunk forward closed over frozen params/batch_stats."""
    jitted = jax.jit(trunk_apply)
    n = len(images)
    feats = []
    for i in range(0, n, batch_size):
        idx = np.arange(i, min(i + batch_size, n))
        real = len(idx)
        if real < batch_size:  # pad to the compiled shape, slice off after
            idx = np.concatenate([idx, np.zeros(batch_size - real, np.int64)])
        out = np.asarray(jitted(jnp.asarray(images[idx])))
        feats.append(out[:real])
    return np.concatenate(feats, axis=0)


def swav_trunk_apply(model, params, batch_stats):
    """Build the frozen eval-mode trunk forward from SwAV train state —
    checkpoint surgery: consume only the ``trunk`` subtree
    (init_model_from_weights capability)."""
    trunk_params = params["trunk"]
    trunk_stats = batch_stats["trunk"]

    def apply(images):
        from dedloc_tpu.models.resnet import ResNet

        return ResNet(model.cfg.trunk, name="trunk").apply(
            {"params": trunk_params, "batch_stats": trunk_stats},
            images,
            False,  # eval mode: frozen BN statistics
        )

    return apply


def run_linear_probe(
    train_features: np.ndarray,  # [N, D]
    train_labels: np.ndarray,  # [N]
    eval_features: np.ndarray,
    eval_labels: np.ndarray,
    num_classes: int,
    args: Optional[LinearProbeArguments] = None,
) -> Dict[str, float]:
    """Train the linear classifier on frozen features; return top-1/top-5.

    SGD + momentum on softmax regression — the standard linear-eval protocol
    behind the MODEL_ZOO numbers (trunk stays frozen; only W, b train).
    """
    args = args or LinearProbeArguments()
    rng = np.random.default_rng(args.seed)
    d = train_features.shape[1]

    params = {
        "w": jnp.zeros((d, num_classes), jnp.float32),
        "b": jnp.zeros((num_classes,), jnp.float32),
    }
    tx = optax.chain(
        optax.add_decayed_weights(args.weight_decay),
        optax.sgd(args.learning_rate, momentum=args.momentum),
    )
    opt_state = tx.init(params)

    @jax.jit
    def train_step(params, opt_state, feats, labels):
        def loss_fn(p):
            logits = feats @ p["w"] + p["b"]
            logp = jax.nn.log_softmax(logits, axis=-1)
            return -jnp.mean(
                jnp.take_along_axis(logp, labels[:, None], axis=-1)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    n = len(train_features)
    bs = min(args.batch_size, n)
    for epoch in range(args.num_epochs):
        order = rng.permutation(n)
        losses = []
        for i in range(0, n - bs + 1, bs):
            idx = order[i : i + bs]
            params, opt_state, loss = train_step(
                params, opt_state,
                jnp.asarray(train_features[idx]),
                jnp.asarray(train_labels[idx]),
            )
            losses.append(float(loss))
        logger.info(
            "linear probe epoch %d: loss %.4f", epoch,
            float(np.mean(losses)) if losses else float("nan"),
        )

    meter = TopKMeter(ks=(1, min(5, num_classes)))
    logits = np.asarray(
        jnp.asarray(eval_features) @ params["w"] + params["b"]
    )
    meter.update(logits, eval_labels)
    result = meter.value()
    logger.info("linear probe eval: %s", result)
    return result
