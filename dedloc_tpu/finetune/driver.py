"""Generic jitted fine-tune loop with early stopping.

The TPU-native stand-in for the reference's HF ``Trainer`` +
``EarlyStoppingCallback`` fine-tune skeleton (train_ner.py:107-125:
load_best_model_at_end, metric_for_best_model="loss", per-epoch eval,
patience 1 / threshold 0.0 defaults): one jitted AdamW train step over
static-shape batches, per-epoch evaluation, best-params restore.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dedloc_tpu.models.albert import classification_loss
from dedloc_tpu.optim.schedules import linear_warmup_linear_decay

logger = logging.getLogger(__name__)


def load_split_examples(dataset_name: str, config_name: str):
    """train/validation examples through the same ``datasets.load_dataset``
    entry point the reference fine-tunes use (train_ner.py / train_ncc.py).
    ``dataset_name`` may be a hub id (networked) or a local directory holding
    ``train.jsonl`` / ``validation.jsonl`` with the dataset's columns, which
    runs the identical Arrow ingestion path offline. Split files are selected
    explicitly (``data_files``) so unrelated files living in the same dir —
    a tokenizer.json, checkpoints — don't get swept into the dataset by
    module inference."""
    import os

    from datasets import load_dataset  # deferred: heavy + networked

    if os.path.isdir(dataset_name):
        if config_name:
            logger.info(
                "dataset config %r ignored for local data-files dir %s",
                config_name,
                dataset_name,
            )

        def split_file(*stems):
            # exact names only (train*.json* would sweep a train_log.jsonl
            # run log or a .json.bak backup into the split); first matching
            # stem wins so validation.jsonl shadows a stale val.jsonl
            for stem in stems:
                for ext in (".jsonl", ".json"):
                    path = os.path.join(dataset_name, stem + ext)
                    if os.path.exists(path):
                        return path
            raise FileNotFoundError(
                f"{dataset_name} has no {stems[0]} data file (expected one "
                f"of: {', '.join(s + e for s in stems for e in ('.jsonl', '.json'))})"
            )

        data_files = {
            "train": split_file("train"),
            "validation": split_file("validation", "val"),
        }
        ds = load_dataset("json", data_files=data_files)
    else:
        ds = load_dataset(dataset_name, config_name)
    return list(ds["train"]), list(ds["validation"])


@dataclasses.dataclass
class FinetuneArguments:
    """Knobs mirroring the fine-tune TrainingArguments the reference sets."""

    learning_rate: float = 5e-5
    weight_decay: float = 0.0
    num_train_epochs: int = 3
    per_device_batch_size: int = 32
    warmup_ratio: float = 0.1
    seed: int = 0
    # EarlyStoppingCallback knobs (train_ner.py:97-104 defaults)
    early_stopping_patience: int = 1
    early_stopping_threshold: float = 0.0
    metric_for_best_model: str = "loss"
    greater_is_better: bool = False
    classifier_dropout: float = 0.1


class EarlyStopping:
    """load_best_model_at_end + EarlyStoppingCallback in one object."""

    def __init__(
        self,
        patience: int = 1,
        threshold: float = 0.0,
        greater_is_better: bool = False,
    ):
        self.patience = patience
        self.threshold = threshold
        self.greater_is_better = greater_is_better
        self.best: Optional[float] = None
        self.bad_evals = 0

    def improved(self, value: float) -> bool:
        if self.best is None:
            return True
        if self.greater_is_better:
            return value > self.best + self.threshold
        return value < self.best - self.threshold

    def record(self, value: float) -> bool:
        """Returns True when training should STOP."""
        if self.improved(value):
            self.best = value
            self.bad_evals = 0
            return False
        self.bad_evals += 1
        return self.bad_evals >= self.patience


def _batches(data: Dict[str, np.ndarray], batch_size: int, rng: np.random.Generator):
    """Shuffled fixed-shape batches; the final ragged batch is wrapped around
    (static shapes keep one compiled program — the TPU constraint the
    reference's pad_to_max_length note points at)."""
    n = len(next(iter(data.values())))
    order = rng.permutation(n)
    if n % batch_size:
        # np.resize tiles the permutation, so this holds even when the pad
        # needed exceeds n (e.g. n=10, batch_size=32)
        order = np.resize(order, n + batch_size - n % batch_size)
    for i in range(0, len(order), batch_size):
        idx = order[i : i + batch_size]
        yield {k: v[idx] for k, v in data.items()}


def make_eval_step(apply_fn: Callable):
    """Jitted eval step. Build ONCE per apply_fn and reuse across evaluate()
    calls — caching on the closure identity (lru_cache) would never hit
    across finetune() calls while pinning dead compiled programs."""

    @jax.jit
    def eval_step(params, batch):
        logits = apply_fn(
            params,
            batch["input_ids"],
            batch["attention_mask"],
            batch.get("token_type_ids"),
        )
        loss, metrics = classification_loss(logits, batch["labels"])
        return jnp.argmax(logits, axis=-1), loss * metrics["n_labels"], metrics[
            "n_labels"
        ]

    return eval_step


def evaluate(
    apply_fn: Callable,
    params,
    data: Dict[str, np.ndarray],
    batch_size: int,
    eval_step: Optional[Callable] = None,
) -> Tuple[float, np.ndarray]:
    """Returns (mean masked loss, predictions over the full set, unshuffled).

    Pass a prebuilt ``eval_step`` (from ``make_eval_step``) to reuse one
    compiled program across epochs."""
    if eval_step is None:
        eval_step = make_eval_step(apply_fn)
    n = len(data["input_ids"])
    preds = []
    total_loss = 0.0
    total_labels = 0.0
    for i in range(0, n, batch_size):
        idx = np.arange(i, min(i + batch_size, n))
        real = len(idx)
        if real < batch_size:  # pad to static shape, then slice off
            idx = np.concatenate([idx, np.zeros(batch_size - real, np.int64)])
        batch = {k: v[idx].copy() for k, v in data.items()}
        batch["labels"][real:] = -100  # padding rows contribute no loss
        p, loss_sum, n_lab = eval_step(params, batch)
        preds.append(np.asarray(p)[:real])
        total_loss += float(loss_sum)
        total_labels += float(n_lab)
    return total_loss / max(1.0, total_labels), np.concatenate(preds, axis=0)


def finetune(
    model,
    init_params,
    train_data: Dict[str, np.ndarray],
    eval_data: Dict[str, np.ndarray],
    args: FinetuneArguments,
    compute_metrics: Optional[Callable[[np.ndarray], Dict[str, float]]] = None,
):
    """Fine-tune ``model`` (a flax Module with the classification call
    signature) and return (best_params, history).

    ``init_params`` may carry a pretrained ``albert`` subtree (the
    collaborative checkpoint); missing heads are freshly initialised.
    ``compute_metrics(predictions)`` turns eval predictions into a metric
    dict (the reference's compute_metrics seam, train_ncc.py:199-205).
    """
    rng = np.random.default_rng(args.seed)
    n = len(train_data["input_ids"])
    steps_per_epoch = max(1, (n + args.per_device_batch_size - 1) // (
        args.per_device_batch_size
    ))
    total_steps = steps_per_epoch * args.num_train_epochs
    schedule = linear_warmup_linear_decay(
        args.learning_rate, int(args.warmup_ratio * total_steps), total_steps
    )
    tx = optax.adamw(schedule, weight_decay=args.weight_decay)

    init_rng = jax.random.PRNGKey(args.seed)
    sample = {
        k: jnp.asarray(v[: args.per_device_batch_size]) for k, v in train_data.items()
    }
    params = model.init(
        {"params": init_rng, "dropout": init_rng},
        sample["input_ids"],
        sample["attention_mask"],
        sample.get("token_type_ids"),
        deterministic=True,
    )["params"]
    if init_params is not None and "albert" in init_params:
        # warm-start the backbone from the pretrained checkpoint; leaf shapes
        # must match the model config exactly — a silently-mismatched
        # position table would clamp under jit instead of erroring
        fresh = jax.tree_util.tree_map(jnp.shape, params["albert"])
        loaded = jax.tree_util.tree_map(jnp.shape, init_params["albert"])
        if fresh != loaded:
            raise ValueError(
                "checkpoint backbone does not match the model config "
                "(e.g. --max_seq_length beyond the pretrained position table, "
                "or a different --model_size than the checkpoint was trained "
                f"with): expected {fresh}, got {loaded}"
            )
        params = dict(params)
        params["albert"] = init_params["albert"]
    opt_state = tx.init(params)

    def apply_train(params, ids, mask, types, dropout_rng):
        return model.apply(
            {"params": params},
            ids,
            mask,
            types,
            deterministic=False,
            rngs={"dropout": dropout_rng},
        )

    def apply_eval(params, ids, mask, types):
        return model.apply({"params": params}, ids, mask, types, deterministic=True)

    eval_step = make_eval_step(apply_eval)  # one compile, reused every epoch

    @jax.jit
    def train_step(params, opt_state, batch, dropout_rng):
        dropout_rng, step_rng = jax.random.split(dropout_rng)

        def loss_fn(p):
            logits = apply_train(
                p,
                batch["input_ids"],
                batch["attention_mask"],
                batch.get("token_type_ids"),
                step_rng,
            )
            loss, metrics = classification_loss(logits, batch["labels"])
            return loss, metrics

        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, metrics, dropout_rng

    stopper = EarlyStopping(
        args.early_stopping_patience,
        args.early_stopping_threshold,
        args.greater_is_better,
    )
    best_params = params
    dropout_rng = jax.random.PRNGKey(args.seed + 1)
    history = []
    for epoch in range(args.num_train_epochs):
        train_loss = 0.0
        steps = 0
        for batch in _batches(train_data, args.per_device_batch_size, rng):
            batch = {k: jnp.asarray(v) for k, v in batch.items()}
            params, opt_state, metrics, dropout_rng = train_step(
                params, opt_state, batch, dropout_rng
            )
            train_loss += float(metrics["loss"])
            steps += 1
        eval_loss, preds = evaluate(
            apply_eval, params, eval_data, args.per_device_batch_size,
            eval_step=eval_step,
        )
        record = {
            "epoch": epoch,
            "train_loss": train_loss / max(1, steps),
            "eval_loss": eval_loss,
        }
        if compute_metrics is not None:
            record.update(compute_metrics(preds))
        history.append(record)
        logger.info("finetune epoch %d: %s", epoch, record)

        key = f"eval_{args.metric_for_best_model}"
        if key in record:
            value = record[key]
        elif args.metric_for_best_model in record:
            value = record[args.metric_for_best_model]
        else:
            # silently substituting eval_loss would invert the optimization
            # direction when greater_is_better=True — fail loudly instead
            raise ValueError(
                f"metric_for_best_model={args.metric_for_best_model!r} not found "
                f"in eval record; available: {sorted(record)}"
            )
        if stopper.improved(value):
            best_params = params
        if stopper.record(value):
            logger.info("early stopping at epoch %d (best=%s)", epoch, stopper.best)
            break
    return best_params, history
