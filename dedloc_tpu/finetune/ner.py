"""Token-classification (NER) fine-tune driver.

Capability parity with sahajbert/train_ner.py: wikiann/bn word-level NER,
label alignment onto sub-tokens (special tokens and continuations -> -100),
pad-to-max static shapes, per-epoch eval with seqeval-style span P/R/F1 and
early stopping on eval loss. The dataset fetch (``driver.load_split_examples``)
takes a hub id or a local data-files dir; offline tests can also inject
word/tag lists directly via ``run_ner``.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from dedloc_tpu.core.config import parse_config
from dedloc_tpu.finetune.driver import (
    FinetuneArguments,
    evaluate,
    finetune,
    load_split_examples,
)
from dedloc_tpu.finetune.metrics import align_labels_with_words, span_f1
from dedloc_tpu.models.albert import AlbertConfig, AlbertForTokenClassification

logger = logging.getLogger(__name__)

# wikiann NER tag set (train_ner.py reads it from dataset features; fixed here
# so offline runs agree with the hub copy)
WIKIANN_LABELS = ["O", "B-PER", "I-PER", "B-ORG", "I-ORG", "B-LOC", "I-LOC"]


@dataclasses.dataclass
class NerArguments:
    model_checkpoint: str = ""  # checkpoint dir; "" = fresh backbone init
    tokenizer_path: str = ""  # tokenizer.json; "" = use model_checkpoint dir
    dataset_name: str = "wikiann"  # hub id or local data-files dir
    dataset_config_name: str = "bn"
    model_size: str = "large"  # AlbertConfig.named: tiny | large
    max_seq_length: int = 128
    label_all_tokens: bool = False
    train: FinetuneArguments = dataclasses.field(default_factory=FinetuneArguments)


def encode_ner_examples(
    examples: Sequence[Dict],
    tokenize_words: Callable[[List[str]], Dict],
    max_seq_length: int,
    label_all_tokens: bool = False,
    sep_token_id: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Word lists + word-level tags -> fixed-shape model arrays.

    ``tokenize_words(words)`` must return {"input_ids", "word_ids"} (the
    is_split_into_words tokenizer contract of train_ner.py:184-191); output is
    padded/truncated to ``max_seq_length``. When truncating, the final
    position becomes ``sep_token_id`` (word_id None, label -100) so long
    inputs keep the pretrained ``[CLS] ... [SEP]`` layout.
    """
    ids = np.zeros((len(examples), max_seq_length), np.int32)
    mask = np.zeros_like(ids)
    labels = np.full_like(ids, -100)
    for i, ex in enumerate(examples):
        enc = tokenize_words(list(ex["tokens"]))
        tok_ids = list(enc["input_ids"])[:max_seq_length]
        word_ids = list(enc["word_ids"])[:max_seq_length]
        if len(enc["input_ids"]) > max_seq_length and sep_token_id is not None:
            tok_ids[-1] = sep_token_id
            word_ids[-1] = None
        lab = align_labels_with_words(word_ids, ex["ner_tags"], label_all_tokens)
        ids[i, : len(tok_ids)] = tok_ids
        mask[i, : len(tok_ids)] = 1
        labels[i, : len(lab)] = lab
    return {"input_ids": ids, "attention_mask": mask, "labels": labels}


def ner_compute_metrics(
    eval_labels: np.ndarray, label_list: Sequence[str] = WIKIANN_LABELS
):
    """compute_metrics seam: drop -100 positions, map ids -> tags, span F1
    (the reference's seqeval post-processing, train_ner.py)."""

    def compute(preds: np.ndarray) -> Dict[str, float]:
        pred_tags, ref_tags = [], []
        for p_row, l_row in zip(preds, eval_labels):
            keep = l_row != -100
            pred_tags.append([label_list[int(p)] for p in p_row[keep]])
            ref_tags.append([label_list[int(l)] for l in l_row[keep]])
        m = span_f1(pred_tags, ref_tags)
        return {f"eval_{k}": v for k, v in m.items()}

    return compute


def run_ner(
    args: NerArguments,
    model_cfg: AlbertConfig,
    train_examples: Sequence[Dict],
    eval_examples: Sequence[Dict],
    tokenize_words: Callable[[List[str]], Dict],
    init_params=None,
    label_list: Sequence[str] = WIKIANN_LABELS,
    sep_token_id: Optional[int] = None,
):
    """Returns (best_params, history). Injectable data/tokenizer for offline
    tests; the CLI main wires wikiann/bn + the trained tokenizer."""
    train_data = encode_ner_examples(
        train_examples, tokenize_words, args.max_seq_length,
        args.label_all_tokens, sep_token_id=sep_token_id,
    )
    eval_data = encode_ner_examples(
        eval_examples, tokenize_words, args.max_seq_length,
        args.label_all_tokens, sep_token_id=sep_token_id,
    )
    model = AlbertForTokenClassification(
        model_cfg, num_labels=len(label_list),
        classifier_dropout=args.train.classifier_dropout,
    )
    return finetune(
        model,
        init_params,
        train_data,
        eval_data,
        args.train,
        compute_metrics=ner_compute_metrics(eval_data["labels"], label_list),
    )


def resolve_tokenizer(tokenizer_path: str, model_checkpoint: str):
    """Load the tokenizer from --tokenizer_path, falling back to the
    checkpoint dir; fail with a clear message rather than an opaque
    tokenizers error when neither is given."""
    from dedloc_tpu.data.tokenizer import load_fast_tokenizer

    path = tokenizer_path or model_checkpoint
    if not path:
        raise ValueError(
            "a trained tokenizer is required: pass --tokenizer_path "
            "(tokenizer.json) or --model_checkpoint (a dir containing one)"
        )
    return load_fast_tokenizer(path)


def load_backbone_params(model_checkpoint: str):
    if not model_checkpoint:
        return None
    from dedloc_tpu.utils.checkpoint import load_latest_checkpoint

    ckpt = load_latest_checkpoint(model_checkpoint)
    return None if ckpt is None else ckpt[1]["params"]


def resolve_model_config(model_size: str, vocab_size: int, max_seq_length: int):
    """--model_size -> AlbertConfig, vocab sized to the tokenizer (the
    reference resizes embeddings for the Bengali vocab the same way,
    sahajbert/run_first_peer.py:76-77). A position table grown past the
    constructor default only applies to fresh backbones — warm starts are
    shape-checked against the checkpoint in driver.finetune."""
    ctor = AlbertConfig.named(model_size)
    cfg = ctor(vocab_size=vocab_size)
    if cfg.max_position_embeddings < max_seq_length:
        cfg = ctor(vocab_size=vocab_size, max_position_embeddings=max_seq_length)
    return cfg


def main(argv=None) -> None:
    from dedloc_tpu.roles.common import force_cpu_if_requested

    force_cpu_if_requested()
    args = parse_config(NerArguments, argv)
    train_examples, eval_examples = load_split_examples(
        args.dataset_name, args.dataset_config_name
    )
    tok = resolve_tokenizer(args.tokenizer_path, args.model_checkpoint)
    init_params = load_backbone_params(args.model_checkpoint)
    _, history = run_ner(
        args,
        resolve_model_config(args.model_size, tok.vocab_size, args.max_seq_length),
        train_examples,
        eval_examples,
        tok.tokenize_words,
        init_params=init_params,
        sep_token_id=tok.sep_id,
    )
    logger.info("NER final: %s", history[-1] if history else {})


if __name__ == "__main__":
    main()
