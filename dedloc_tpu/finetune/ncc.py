"""Sequence-classification (news category) fine-tune driver.

Capability parity with sahajbert/train_ncc.py: indic_glue sna.bn sequence
classification with AutoModelForSequenceClassification-equivalent head,
accuracy metric (train_ncc.py:197-205), early stopping on eval loss.
"""
from __future__ import annotations

import dataclasses
import logging
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from dedloc_tpu.core.config import parse_config
from dedloc_tpu.finetune.driver import (
    FinetuneArguments,
    finetune,
    load_split_examples,
)
from dedloc_tpu.finetune.metrics import accuracy_score
from dedloc_tpu.models.albert import AlbertConfig, AlbertForSequenceClassification

logger = logging.getLogger(__name__)

# indic_glue sna.bn label set (soham news article categories)
SNA_BN_LABELS = ["kolkata", "state", "national", "international", "sports", "entertainment"]


@dataclasses.dataclass
class NccArguments:
    model_checkpoint: str = ""  # checkpoint dir; "" = fresh backbone init
    tokenizer_path: str = ""  # tokenizer.json; "" = use model_checkpoint dir
    dataset_name: str = "indic_glue"  # hub id or local data-files dir
    dataset_config_name: str = "sna.bn"
    model_size: str = "large"  # AlbertConfig.named: tiny | large
    max_seq_length: int = 128
    train: FinetuneArguments = dataclasses.field(default_factory=FinetuneArguments)


def encode_ncc_examples(
    examples: Sequence[Dict],
    tokenize_text: Callable[[str], Sequence[int]],
    max_seq_length: int,
    sep_token_id: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """(text, label) pairs -> fixed-shape arrays for the pooled classifier.

    When truncating, the final position is rewritten to ``sep_token_id`` so
    long inputs keep the ``[CLS] ... [SEP]`` layout the backbone was
    pretrained on (HF truncation preserves special tokens the same way).
    """
    ids = np.zeros((len(examples), max_seq_length), np.int32)
    mask = np.zeros_like(ids)
    labels = np.zeros((len(examples),), np.int32)
    for i, ex in enumerate(examples):
        tok_ids = list(tokenize_text(ex["text"]))
        if len(tok_ids) > max_seq_length:
            tok_ids = tok_ids[:max_seq_length]
            if sep_token_id is not None:
                tok_ids[-1] = sep_token_id
        ids[i, : len(tok_ids)] = tok_ids
        mask[i, : len(tok_ids)] = 1
        labels[i] = int(ex["label"])
    return {"input_ids": ids, "attention_mask": mask, "labels": labels}


def ncc_compute_metrics(eval_labels: np.ndarray):
    def compute(preds: np.ndarray) -> Dict[str, float]:
        return {
            "eval_accuracy": accuracy_score(
                [int(p) for p in preds], [int(l) for l in eval_labels]
            )
        }

    return compute


def run_ncc(
    args: NccArguments,
    model_cfg: AlbertConfig,
    train_examples: Sequence[Dict],
    eval_examples: Sequence[Dict],
    tokenize_text: Callable[[str], Sequence[int]],
    init_params=None,
    label_list: Sequence[str] = SNA_BN_LABELS,
    sep_token_id: Optional[int] = None,
):
    train_data = encode_ncc_examples(
        train_examples, tokenize_text, args.max_seq_length,
        sep_token_id=sep_token_id,
    )
    eval_data = encode_ncc_examples(
        eval_examples, tokenize_text, args.max_seq_length,
        sep_token_id=sep_token_id,
    )
    model = AlbertForSequenceClassification(
        model_cfg, num_labels=len(label_list),
        classifier_dropout=args.train.classifier_dropout,
    )
    return finetune(
        model,
        init_params,
        train_data,
        eval_data,
        args.train,
        compute_metrics=ncc_compute_metrics(eval_data["labels"]),
    )


def main(argv=None) -> None:
    from dedloc_tpu.roles.common import force_cpu_if_requested

    force_cpu_if_requested()
    args = parse_config(NccArguments, argv)
    train_examples, eval_examples = load_split_examples(
        args.dataset_name, args.dataset_config_name
    )
    from dedloc_tpu.finetune.ner import (
        load_backbone_params,
        resolve_model_config,
        resolve_tokenizer,
    )

    tok = resolve_tokenizer(args.tokenizer_path, args.model_checkpoint)
    init_params = load_backbone_params(args.model_checkpoint)
    _, history = run_ncc(
        args,
        resolve_model_config(args.model_size, tok.vocab_size, args.max_seq_length),
        train_examples,
        eval_examples,
        tok.encode_ids,
        init_params=init_params,
        sep_token_id=tok.sep_id,
    )
    logger.info("NCC final: %s", history[-1] if history else {})


if __name__ == "__main__":
    main()
