"""Pure-numpy evaluation metrics for downstream tasks.

Replaces the reference's external metric dependencies: ``seqeval`` entity-span
precision/recall/F1 (train_ner.py uses load_metric("seqeval")) and
``accuracy`` (train_ncc.py:197). Span extraction follows the IOB2/BIO scheme
seqeval defaults to: an entity is a maximal run ``B-X (I-X)*``; a bare ``I-X``
(or an ``I-X`` after a different type) opens a new entity, matching seqeval's
lenient default mode.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Set, Tuple

Entity = Tuple[str, int, int]  # (type, start, end_exclusive)


def extract_entities(tags: Sequence[str]) -> Set[Entity]:
    """BIO tag sequence -> set of (type, start, end) spans."""
    entities: Set[Entity] = set()
    start = None
    etype = None
    for i, tag in enumerate(tags):
        if tag.startswith("B-"):
            if start is not None:
                entities.add((etype, start, i))
            start, etype = i, tag[2:]
        elif tag.startswith("I-"):
            if start is None or etype != tag[2:]:
                # orphan continuation: seqeval's default counts it as a span
                if start is not None:
                    entities.add((etype, start, i))
                start, etype = i, tag[2:]
        else:  # "O" or anything else closes the open span
            if start is not None:
                entities.add((etype, start, i))
                start, etype = None, None
    if start is not None:
        entities.add((etype, start, len(tags)))
    return entities


def span_f1(
    predictions: Sequence[Sequence[str]], references: Sequence[Sequence[str]]
) -> Dict[str, float]:
    """Micro precision/recall/F1 over entity spans + token accuracy."""
    assert len(predictions) == len(references)
    tp = fp = fn = 0
    correct = total = 0
    for pred, ref in zip(predictions, references):
        assert len(pred) == len(ref)
        p_ents = extract_entities(pred)
        r_ents = extract_entities(ref)
        tp += len(p_ents & r_ents)
        fp += len(p_ents - r_ents)
        fn += len(r_ents - p_ents)
        correct += sum(p == r for p, r in zip(pred, ref))
        total += len(ref)
    precision = tp / max(1, tp + fp)
    recall = tp / max(1, tp + fn)
    f1 = 2 * precision * recall / max(1e-12, precision + recall)
    return {
        "precision": precision,
        "recall": recall,
        "f1": f1,
        "accuracy": correct / max(1, total),
    }


def accuracy_score(predictions: Sequence[int], references: Sequence[int]) -> float:
    assert len(predictions) == len(references)
    if not references:
        return 0.0
    return sum(p == r for p, r in zip(predictions, references)) / len(references)


def align_labels_with_words(
    word_ids: Sequence[object],
    word_labels: Sequence[int],
    label_all_tokens: bool = False,
    ignore_index: int = -100,
) -> List[int]:
    """Word-level labels -> token-level labels via the tokenizer's word_ids.

    The label-alignment rule of train_ner.py:184-212: special tokens
    (word_id None) get -100; the first sub-token of each word gets the word's
    label; continuation sub-tokens get the label if ``label_all_tokens`` else
    -100.
    """
    out: List[int] = []
    prev = None
    for wid in word_ids:
        if wid is None:
            out.append(ignore_index)
        elif wid != prev:
            out.append(word_labels[wid])
        else:
            out.append(word_labels[wid] if label_all_tokens else ignore_index)
        prev = wid
    return out
