"""Downstream fine-tuning of collaboratively pretrained checkpoints.

Capability parity with the reference's evaluation scripts
(sahajbert/train_ner.py — wikiann/bn token classification with seqeval
P/R/F1 + early stopping; sahajbert/train_ncc.py — indic_glue sna.bn
sequence classification with accuracy), rebuilt as jitted JAX loops with
static shapes (pad-to-max, the TPU-friendly layout the reference's
``pad_to_max_length`` flag notes is required on TPU).
"""
from dedloc_tpu.finetune.driver import (  # noqa: F401
    EarlyStopping,
    FinetuneArguments,
    evaluate,
    finetune,
)
from dedloc_tpu.finetune.metrics import (  # noqa: F401
    accuracy_score,
    extract_entities,
    span_f1,
)
from dedloc_tpu.finetune.linear_probe import (  # noqa: F401
    LinearProbeArguments,
    TopKMeter,
    extract_features,
    run_linear_probe,
)
