"""Headline benchmark: ALBERT-large pretraining throughput on one chip.

Measures samples/sec of the full jitted train step (forward, backward, grad
accumulation, LAMB) on ALBERT-large at seq_length 512 — the reference's
canonical per-peer workload (albert/arguments.py:104-121: per-device batch 4 ×
grad_accum 2, fp16, LAMB lr 1.76e-3). On TPU we run the same recipe with a
larger per-chip micro-batch (bf16 compute, scan-shared layers, remat), since a
TPU chip replaces a whole T4 GPU peer.

Baseline anchor: the reference peer is a T4 (g4dn.2xlarge, AWS_runner.ipynb).
A T4 running ALBERT-large seq-512 MLM+SOP fp16 sustains ≈10 samples/sec
(≈0.9 TFLOP/sample forward+backward against ≈9 effective TFLOP/s) — the same
arithmetic the DeDLOC paper's fleet sizing implies. vs_baseline is measured
samples/sec divided by that 10 samples/sec/peer anchor.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
from __future__ import annotations

import json
import os
import time

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # honor an explicit CPU request: the container's sitecustomize pins
    # jax_platforms to the TPU plugin, so the env var alone is not enough
    # (same workaround as tests/conftest.py and __graft_entry__.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

T4_BASELINE_SAMPLES_PER_SEC = 10.0

# bf16 peak TFLOP/s per chip, keyed by PJRT device_kind substring. Used for
# the MFU report (model FLOPs / peak), NOT for throughput measurement.
TPU_PEAK_TFLOPS = (
    ("v5 lite", 197.0),  # v5e
    ("v5e", 197.0),
    ("v5p", 459.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 46.0),
    ("v6 lite", 918.0),  # trillium
)


def chip_peak_tflops() -> float:
    kind = jax.devices()[0].device_kind.lower()
    for sub, peak in TPU_PEAK_TFLOPS:
        if sub in kind:
            return peak
    return 0.0  # unknown chip (or CPU smoke): MFU omitted


def albert_train_flops_per_sample(cfg, seq: int, max_pred: int) -> float:
    """Analytic MODEL FLOPs for one fwd+bwd sample (matmuls only, the MXU
    work; remat recompute is intentionally excluded — MFU measures useful
    FLOPs against peak, so recompute shows up as lower MFU, matching the
    convention of the scaling-book / PaLM appendix)."""
    h, i, s = cfg.hidden_size, cfg.intermediate_size, seq
    e, v = cfg.embedding_size, cfg.vocab_size
    per_token_layer = (
        8 * h * h  # QKV + attention-output projections
        + 4 * h * s  # QK^T scores + attention-weighted values
        + 4 * h * i  # FFN in + out
    )
    fwd = cfg.num_hidden_layers * per_token_layer * s
    fwd += 2 * e * h * s  # factorized embedding projection
    fwd += max_pred * 2 * (h * e + e * v)  # gathered MLM head
    fwd += 2 * h * 2  # SOP head (negligible)
    return 3.0 * fwd  # bwd = 2x fwd matmul FLOPs


def run_codec() -> None:
    """Reproducible wire-path bench (DEDLOC_BENCH=codec): serialize +
    deserialize the ALBERT-large param tree (~17.8M fp32 params, matching
    what a peer actually ships per averaging round) through the fp16+CRC32C
    wire codec (native/wirecodec.cpp with numpy fallback). Baseline anchor:
    round-1 measured 102 ms serialize on the same-sized tree (BASELINE.md)."""
    from dedloc_tpu.core.serialization import (
        CompressionType,
        deserialize_tree,
        serialize_tree,
    )

    rng = np.random.default_rng(0)
    # ALBERT-large's tensors: embeddings + factorized proj + the one shared
    # layer + pooler + MLM head ≈ 17.8M params (full tree is 17.97M)
    tree = {
        "word_embeddings": rng.standard_normal((30000, 128)).astype(np.float32),
        "position_embeddings": rng.standard_normal((512, 128)).astype(np.float32),
        "token_type_embeddings": rng.standard_normal((2, 128)).astype(np.float32),
        "embedding_projection": rng.standard_normal((128, 1024)).astype(np.float32),
        "attn_qkv": rng.standard_normal((3, 1024, 1024)).astype(np.float32),
        "attn_out": rng.standard_normal((1024, 1024)).astype(np.float32),
        "ffn_in": rng.standard_normal((1024, 4096)).astype(np.float32),
        "ffn_out": rng.standard_normal((4096, 1024)).astype(np.float32),
        "pooler": rng.standard_normal((1024, 1024)).astype(np.float32),
        "mlm_dense": rng.standard_normal((1024, 128)).astype(np.float32),
        "mlm_bias": rng.standard_normal((30000,)).astype(np.float32),
    }
    n_params = sum(int(v.size) for v in tree.values())
    blob = serialize_tree(tree, CompressionType.FLOAT16)  # warm the codec
    ser = des = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        blob = serialize_tree(tree, CompressionType.FLOAT16)
        ser = min(ser, time.perf_counter() - t0)
        t0 = time.perf_counter()
        deserialize_tree(blob)
        des = min(des, time.perf_counter() - t0)
    print(json.dumps({
        "metric": "wirecodec_fp16_serialize_ms",
        "value": round(ser * 1e3, 2),
        "unit": "ms",
        "vs_baseline": round(102.0 / (ser * 1e3), 3),
        "deserialize_ms": round(des * 1e3, 2),
        "n_params": n_params,
        "wire_mb": round(len(blob) / 2**20, 1),
    }))


def run_allreduce_pipeline() -> None:
    """Wire-path bench (DEDLOC_BENCH=allreduce_pipeline): a full multi-peer
    group all-reduce over localhost RPC — matchmaking excluded, so the
    number tracks the averaging WIRE PATH (chunk streaming + compression +
    eager reduce), not the codec in isolation (DEDLOC_BENCH=codec) and not
    group formation.

    Reports one JSON line with (a) wire bytes per round at each compression
    level and (b) round wall time for the chunk-streamed pipeline vs the
    monolithic-span wire format under a simulated volunteer link (per-peer
    serialized uplink: fixed per-message latency + bandwidth-proportional
    transmission — the regime DeDLOC targets). vs_baseline is the
    pipeline's speedup over the monolithic path on the same link.
    """
    import asyncio

    import numpy as np

    from dedloc_tpu.averaging.allreduce import GroupAllReduce
    from dedloc_tpu.core.serialization import CompressionType
    from dedloc_tpu.dht.protocol import RPCClient, RPCServer

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    # DEDLOC_BENCH_TIMING=0 skips the link-simulation half (part b below):
    # the wire-bytes half is deterministic and cheap, the timing half costs
    # seconds of simulated uplink sleeps — tier-1's contract test only
    # asserts the former
    timing = os.environ.get("DEDLOC_BENCH_TIMING", "1") != "0"
    n_peers = 3
    # bandwidth-weighted spans, the DeDLOC fleet shape: a big-pipe donor
    # (an aux-style peer) hosts most of the vector, so its SERVE leg is the
    # round's long pole — exactly where streaming reduced chunks back while
    # the scatter is still inbound pays off. Symmetric groups barely gain
    # (every uplink carries scatter+serve either way).
    peer_bandwidths = [1.0, 1.0, 8.0]
    if tiny:
        dim, chunk, rounds = 524_288, 65_536, 2  # 2 MB fp32
        bandwidth, latency = 8e6, 0.3e-3
    else:
        dim, chunk, rounds = 4_194_304, 131_072, 3  # 16 MB fp32
        bandwidth, latency = 25e6, 1e-3

    class LinkSim:
        """Per-peer serialized uplink: one transmission at a time, each
        costing latency + nbytes/bandwidth. Loopback RPC underneath stays
        real — this only adds the volunteer-link wait."""

        def __init__(self, n):
            self.locks = [asyncio.Lock() for _ in range(n)]

        async def transmit(self, peer, nbytes):
            async with self.locks[peer]:
                await asyncio.sleep(latency + nbytes / bandwidth)

    class MeteredClient(RPCClient):
        """Counts averaging wire bytes and (optionally) simulates the link."""

        def __init__(self, me, port_to_peer, wire, link=None):
            super().__init__(request_timeout=60.0)
            self._me = me
            self._port_to_peer = port_to_peer
            self._wire = wire
            self._link = link

        async def call(self, endpoint, method, args=None, timeout=None):
            if method == "avg.part" and args and args.get("data") is not None:
                nbytes = len(args["data"])
                self._wire["bytes"] += nbytes
                if self._link is not None:
                    await self._link.transmit(self._me, nbytes)
            reply = await super().call(endpoint, method, args, timeout)
            if method == "avg.get_reduced":
                nbytes = len(reply["data"])
                self._wire["bytes"] += nbytes
                if self._link is not None:
                    # the reduced chunk rides the HOST's uplink
                    await self._link.transmit(
                        self._port_to_peer[endpoint[1]], nbytes
                    )
            return reply

    async def one_round(compression, chunk_size, link_enabled, round_id):
        rng = np.random.default_rng(0)
        vectors = [
            rng.standard_normal(dim).astype(np.float32)
            for _ in range(n_peers)
        ]
        servers, clients, reducers = [], [], []
        wire = {"bytes": 0}
        link = LinkSim(n_peers) if link_enabled else None
        for i in range(n_peers):
            server = RPCServer("127.0.0.1", 0)
            await server.start()
            servers.append(server)
        port_to_peer = {s.port: i for i, s in enumerate(servers)}
        endpoints = [("127.0.0.1", s.port) for s in servers]
        for i in range(n_peers):
            client = MeteredClient(i, port_to_peer, wire, link)
            clients.append(client)
            reducers.append(
                GroupAllReduce(
                    client, servers[i], compression=compression,
                    timeout=120.0, chunk_size=chunk_size,
                )
            )
        try:
            t0 = time.perf_counter()
            await asyncio.gather(
                *(
                    reducers[i].run(
                        round_id, i, vectors[i], 1.0, endpoints,
                        peer_bandwidths,
                    )
                    for i in range(n_peers)
                )
            )
            wall = time.perf_counter() - t0
        finally:
            for c in clients:
                await c.close()
            for s in servers:
                await s.stop()
        return wall, wire["bytes"]

    async def bench():
        # (a) wire bytes per round, per compression level (no link sim)
        wire_bytes = {}
        loopback_wall = float("inf")
        for level in (
            CompressionType.NONE, CompressionType.FLOAT16,
            CompressionType.UINT8,
        ):
            wall, nbytes = await one_round(
                level, chunk, False, f"wb-{level.value}"
            )
            wire_bytes[level.value] = nbytes
            if level is CompressionType.FLOAT16:
                loopback_wall = wall

        # (b) chunk-streamed pipeline vs monolithic spans on the same
        # simulated link (float16, the shipped default)
        if not timing:
            return wire_bytes, loopback_wall, 0.0, 0.0
        pipelined = monolithic = float("inf")
        for r in range(rounds):
            wall, _ = await one_round(
                CompressionType.FLOAT16, chunk, True, f"pipe-{r}"
            )
            pipelined = min(pipelined, wall)
            wall, _ = await one_round(
                CompressionType.FLOAT16, 0, True, f"mono-{r}"
            )
            monolithic = min(monolithic, wall)
        return wire_bytes, loopback_wall, pipelined, monolithic

    wire_bytes, loopback_wall, pipelined, monolithic = asyncio.run(bench())
    # effective rate: raw fp32 gradient bytes averaged per second of round
    # wall time, per peer (the number a volunteer's step budget feels);
    # with the link sim skipped it reflects the bare loopback round
    effective = dim * 4 / (pipelined if timing else loopback_wall)
    print(json.dumps({
        "metric": "allreduce_pipeline_effective_bytes_per_sec",
        "value": round(effective, 1),
        "unit": "bytes/sec",
        # speedup of the chunk-streamed pipeline over the monolithic-span
        # wire format under the same per-message-latency link (0.0 when the
        # timing half was skipped via DEDLOC_BENCH_TIMING=0)
        "vs_baseline": round(monolithic / pipelined, 3) if timing else 0.0,
        "wire_bytes_per_round": wire_bytes,
        "pipelined_wall_ms": round(pipelined * 1e3, 2),
        "monolithic_wall_ms": round(monolithic * 1e3, 2),
        "peers": n_peers,
        "vector_bytes": dim * 4,
        "chunk_elems": chunk,
    }))


def run_grad_pipeline() -> None:
    """Boundary-seam bench (DEDLOC_BENCH=grad_pipeline): the gradient
    device->host seam at an averaging boundary — legacy per-leaf
    ``device_get`` + host ``TreeLayout.flatten_into`` vs the device-resident
    flat pipeline (``averaging/device_flat.py``: fused on-device
    flatten+mean+quantize, chunked async D2H, decode-only host leg) — over
    the ALBERT-large gradient tree (~17.9M fp32 params, the tree a peer
    actually ships per round).

    Reports (a) D2H bytes per boundary for each path (deterministic — the
    tier-1 contract half; under fp16/uint8 wire formats the pipeline moves
    2-4x fewer bytes because quantization happens BEFORE the transfer) and
    (b) best-of wall to contribution-ready on the host
    (DEDLOC_BENCH_TIMING=0 skips). vs_baseline is legacy wall / pipeline
    wall — meaningful on a real PCIe/tunnel link where bytes dominate; on
    a CPU backend both paths are memcpy-bound and the ratio hovers near 1.
    ``DEDLOC_BENCH_COMPRESSION`` picks the wire format (default float16).
    """
    import jax.numpy as jnp

    from dedloc_tpu.averaging.device_flat import DeviceFlatPipeline
    from dedloc_tpu.averaging.partition import TreeLayout
    from dedloc_tpu.collaborative.optimizer import _tree_to_named

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    timing = os.environ.get("DEDLOC_BENCH_TIMING", "1") != "0"
    compression = os.environ.get("DEDLOC_BENCH_COMPRESSION", "float16")
    rng = np.random.default_rng(0)
    scale = 0.01 if tiny else 1.0

    def t(*shape):
        shape = tuple(max(1, int(d * scale)) for d in shape)
        return jnp.asarray(
            rng.standard_normal(shape).astype(np.float32)
        )

    # the ALBERT-large gradient tree shape (run_codec's tree, as grads)
    tree = {
        "word_embeddings": t(30000, 128),
        "position_embeddings": t(512, 128),
        "token_type_embeddings": t(2, 128),
        "embedding_projection": t(128, 1024),
        "attn_qkv": t(3, 1024, 1024) if not tiny else t(3, 32, 32),
        "attn_out": t(1024, 1024),
        "ffn_in": t(1024, 4096),
        "ffn_out": t(4096, 1024),
        "pooler": t(1024, 1024),
        "mlm_dense": t(1024, 128),
        "mlm_bias": t(30000),
    }
    n_micro = 16
    n_params = sum(int(v.size) for v in jax.tree.leaves(tree))

    def legacy_boundary():
        mean = jax.tree.map(lambda g: g / n_micro, tree)
        named = _tree_to_named(mean)  # per-leaf device_get
        layout = TreeLayout.for_tree(named)
        return layout.flatten_into(named)

    pipe = DeviceFlatPipeline.for_tree(tree, compression=compression)

    def pipeline_boundary():
        fetch = pipe.fetch(tree, n=n_micro, use_ef=False)
        return fetch, fetch.result().flat

    # warm both paths (jit compile, buffer alloc)
    legacy_flat = legacy_boundary()
    fetch, pipe_flat = pipeline_boundary()
    np.testing.assert_allclose(pipe_flat, legacy_flat, atol=1e-2)
    legacy_bytes = n_params * 4  # fp32 over the seam, per-leaf
    pipeline_bytes = fetch.wire_bytes

    legacy_wall = pipe_wall = float("inf")
    iters = 1 if tiny else 3
    if timing:
        for _ in range(iters):
            t0 = time.perf_counter()
            legacy_boundary()
            legacy_wall = min(legacy_wall, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pipeline_boundary()
            pipe_wall = min(pipe_wall, time.perf_counter() - t0)

    print(json.dumps({
        "metric": "grad_pipeline_d2h_bytes_per_boundary",
        "value": pipeline_bytes,
        "unit": "bytes",
        # byte reduction is the load-bearing, hardware-independent number;
        # the wall ratio below only speaks on a real device link
        "vs_baseline": round(legacy_bytes / pipeline_bytes, 3),
        "compression": compression,
        "n_params": n_params,
        "legacy_d2h_bytes": legacy_bytes,
        "legacy_wall_ms": (
            round(legacy_wall * 1e3, 2) if timing else 0.0
        ),
        "pipeline_wall_ms": (
            round(pipe_wall * 1e3, 2) if timing else 0.0
        ),
        "chunks": len(pipe.bounds),
    }))


def run_checkpoint_restore() -> None:
    """Swarm-checkpoint restore bench (DEDLOC_BENCH=checkpoint_restore):
    bootstrap bytes + wall for a joiner restoring the collaboration state,
    1-provider monolithic blob vs N-provider sharded
    (dedloc_tpu/checkpointing) — the availability cliff this subsystem
    removes: the blob path downloads everything from ONE peer's uplink,
    the sharded path spreads distinct shards across every announcing
    provider.

    Link model: per-provider serialized uplink (fixed per-message latency +
    bandwidth-proportional transmission), the same volunteer-link shape as
    the allreduce_pipeline bench; DEDLOC_BENCH_TIMING=0 skips the link-sim
    sleeps and reports only the deterministic byte/provider accounting
    (tier-1's contract half). vs_baseline is monolithic wall / sharded wall
    on the same link — ~N for N equal providers.
    """
    import asyncio
    import hashlib

    import numpy as np

    from dedloc_tpu.checkpointing import (
        CheckpointAnnouncement,
        build_manifest,
        shard_bytes,
        sharded_restore,
    )
    from dedloc_tpu.core.serialization import (
        CompressionType,
        serialize_array,
        serialize_tree,
    )
    from dedloc_tpu.dht.protocol import RPCClient, RPCServer

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    timing = os.environ.get("DEDLOC_BENCH_TIMING", "1") != "0"
    n_providers = int(os.environ.get("DEDLOC_BENCH_PROVIDERS", "4"))
    if tiny:
        dim, shard_elems = 262_144, 32_768  # 1 MB fp32, 8 shards
        bandwidth, latency = 8e6, 0.3e-3
    else:
        dim, shard_elems = 8_388_608, 1_048_576  # 32 MB fp32, 8 shards
        bandwidth, latency = 25e6, 1e-3

    rng = np.random.default_rng(0)
    tree = {"flat/params": rng.standard_normal(dim).astype(np.float32)}
    metadata = {"step": 1000, "local_step": 1000}
    manifest, flat = build_manifest(tree, 1000, shard_size=shard_elems,
                                    metadata=metadata)
    blob = serialize_tree(tree, CompressionType.NONE)
    blob_digest = hashlib.sha256(blob).digest()

    class LinkSim:
        """One serialized uplink per provider (allreduce_pipeline's model)."""

        def __init__(self, n):
            self.locks = [asyncio.Lock() for _ in range(n)]

        async def transmit(self, provider, nbytes):
            async with self.locks[provider]:
                await asyncio.sleep(latency + nbytes / bandwidth)

    class MeteredClient(RPCClient):
        """Counts restore wire bytes; reply payloads ride the serving
        provider's simulated uplink."""

        def __init__(self, port_to_provider, wire, link=None):
            super().__init__(request_timeout=120.0)
            self._port_to_provider = port_to_provider
            self._wire = wire
            self._link = link

        async def call(self, endpoint, method, args=None, timeout=None):
            reply = await super().call(endpoint, method, args, timeout)
            payload = None
            if method == "ckpt.shard":
                payload = reply["data"]
            elif method == "ckpt.manifest":
                payload = reply["manifest"]
            elif method == "state.get":
                payload = reply["state"]
            if payload is not None:
                self._wire["bytes"] += len(payload)
                if self._link is not None:
                    await self._link.transmit(
                        self._port_to_provider[endpoint[1]], len(payload)
                    )
            return reply

    async def start_providers(n):
        servers = []

        async def get_manifest(peer, args):
            return {"manifest": manifest.to_bytes()}

        async def get_shard(peer, args):
            index = int(args["index"])
            raw = shard_bytes(flat, manifest, index)
            return {
                "index": index,
                "data": serialize_array(
                    np.frombuffer(raw, dtype=np.float32), CompressionType.NONE
                ),
            }

        async def get_state(peer, args):
            return {"state": blob, "checksum": blob_digest}

        for _ in range(n):
            server = RPCServer("127.0.0.1", 0)
            server.register("ckpt.manifest", get_manifest)
            server.register("ckpt.shard", get_shard)
            server.register("state.get", get_state)
            await server.start()
            servers.append(server)
        return servers

    async def bench():
        servers = await start_providers(n_providers)
        port_to_provider = {s.port: i for i, s in enumerate(servers)}
        endpoints = [("127.0.0.1", s.port) for s in servers]
        try:
            # monolithic: the whole blob from provider 0's uplink
            mono_wire = {"bytes": 0}
            client = MeteredClient(
                port_to_provider, mono_wire,
                LinkSim(n_providers) if timing else None,
            )
            t0 = time.perf_counter()
            reply = await client.call(endpoints[0], "state.get", {})
            assert hashlib.sha256(reply["state"]).digest() == blob_digest
            mono_wall = time.perf_counter() - t0
            await client.close()

            # sharded: distinct shards from every provider in parallel
            shard_wire = {"bytes": 0}
            client = MeteredClient(
                port_to_provider, shard_wire,
                LinkSim(n_providers) if timing else None,
            )
            anns = [
                CheckpointAnnouncement(
                    step=manifest.step, manifest_digest=manifest.digest(),
                    num_shards=manifest.num_shards, endpoint=list(ep),
                )
                for ep in endpoints
            ]
            t0 = time.perf_counter()
            _meta, restored, _m = await sharded_restore(
                client, anns, parallelism=n_providers * 2, retries=1,
            )
            shard_wall = time.perf_counter() - t0
            np.testing.assert_array_equal(
                restored["flat/params"], tree["flat/params"]
            )
            await client.close()
            return mono_wall, mono_wire["bytes"], shard_wall, \
                shard_wire["bytes"]
        finally:
            for s in servers:
                await s.stop()

    mono_wall, mono_bytes, shard_wall, shard_bytes_total = asyncio.run(bench())
    print(json.dumps({
        "metric": "checkpoint_restore_sharded_bytes_per_sec",
        "value": round(manifest.total_bytes / shard_wall, 1),
        "unit": "bytes/sec",
        # sharded restore speedup over the single-provider blob on the same
        # per-provider-uplink link model (0.0 when timing was skipped)
        "vs_baseline": round(mono_wall / shard_wall, 3) if timing else 0.0,
        "state_bytes": manifest.total_bytes,
        "num_shards": manifest.num_shards,
        "monolithic": {"providers": 1, "wire_bytes": mono_bytes,
                       "wall_ms": round(mono_wall * 1e3, 2)},
        "sharded": {"providers": n_providers,
                    "wire_bytes": shard_bytes_total,
                    "wall_ms": round(shard_wall * 1e3, 2)},
    }))


def run_swav() -> None:
    """SwAV ResNet-50 step bench (DEDLOC_BENCH=swav): the full jitted
    multicrop train step — trunk fwd/bwd over 2x224 + 6x96 crops, prototypes
    head, sinkhorn assignment in the loss, LARS update, prototype
    re-normalization (swav_1node_resnet_submit.yaml recipe).

    MFU uses XLA's own executed-FLOP count for the compiled step (convs
    dominate; an analytic count would re-derive ResNet-50 conv by conv).
    vs_baseline anchors on the SwAV paper's own wall-clock: 800 epochs of
    ImageNet-1k on 64 V100s in ~50 h => ~88 samples/s per V100 peer."""
    from dedloc_tpu.models.swav import (
        SwAVConfig,
        SwAVModel,
        SwAVQueue,
        SwAVTrainState,
        make_swav_train_step,
    )
    from dedloc_tpu.optim import lars

    V100_SWAV_SAMPLES_PER_SEC = 88.0
    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    if tiny:
        cfg = SwAVConfig.tiny()
        sizes, counts = (32, 16), (2, 2)
        batch, iters = 4, 2
    else:
        cfg = SwAVConfig(queue_length=3840)
        sizes, counts = (224, 96), (2, 6)
        # throughput saturates by B=128 (365/510/591/608 samples/s at
        # B=16/32/64/128 on v5e, 2026-07-30)
        batch = int(os.environ.get("DEDLOC_BENCH_BATCH", "128"))
        iters = 5

    model = SwAVModel(cfg)
    rng = jax.random.PRNGKey(0)
    crops = [
        jax.random.normal(
            jax.random.PRNGKey(i), (count * batch, size, size, 3),
            jnp.float32,
        )
        for i, (size, count) in enumerate(zip(sizes, counts))
    ]
    variables = model.init(rng, crops, True)
    tx = lars(learning_rate=0.6, momentum=0.9, weight_decay=1e-6)
    state = jax.jit(
        lambda p, bn: SwAVTrainState(
            step=jnp.zeros([], jnp.int32),
            params=p,
            batch_stats=bn,
            opt_state=tx.init(p),
            queue=SwAVQueue.create(cfg, jax.random.PRNGKey(1))
            if cfg.queue_length else None,
        )
    )(variables["params"], variables["batch_stats"])
    step = make_swav_train_step(model, cfg, tx)

    state, metrics = step(state, crops, False)
    float(metrics["loss"])  # settle through the tunnel

    best = float("inf")
    for block in range(3):
        start = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, crops, False)
        float(metrics["loss"])
        best = min(best, time.perf_counter() - start)
    samples_per_sec = iters * batch / best

    result = {
        "metric": "swav_resnet50_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 3),
        "unit": "samples/sec",
        "vs_baseline": round(samples_per_sec / V100_SWAV_SAMPLES_PER_SEC, 3),
    }
    peak = chip_peak_tflops()
    if peak and not tiny:
        try:
            analysis = step.lower(state, crops, False).compile().cost_analysis()
            if isinstance(analysis, list):
                analysis = analysis[0]
            flops_step = float(analysis.get("flops", 0.0))
            if flops_step > 0:
                result["mfu"] = round(
                    samples_per_sec * flops_step / batch / (peak * 1e12), 4
                )
                result["model_tflops_per_sample"] = round(
                    flops_step / batch / 1e12, 4
                )
        except Exception:
            pass
        result["chip"] = jax.devices()[0].device_kind
    print(json.dumps(result))


def run_longctx() -> None:
    """Long-context bench (DEDLOC_BENCH=longctx): ALBERT-large fwd+bwd at
    S=16,384 on ONE chip via the Pallas flash kernel — the length dense
    attention cannot even allocate at (BASELINE.md feasibility row, now a
    reproducible number). Reports tokens/sec; vs_baseline is against the
    reference's fixed S=512 capability (albert/arguments.py:110): it has NO
    long-context path, so the anchor is this workload's own S=512 rate and
    the ratio shows the cost of 32x longer context."""
    from dedloc_tpu.data.mlm import max_predictions_for
    from dedloc_tpu.models.albert import (
        AlbertConfig,
        AlbertForPreTraining,
        albert_pretraining_loss_gathered,
    )

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    seq = 1024 if tiny else int(os.environ.get("DEDLOC_BENCH_SEQ", "16384"))
    per_step = 1
    impl = "flash" if jax.default_backend() == "tpu" else "dense"
    if impl == "dense" and seq > 4096:
        raise SystemExit(
            "longctx bench off-TPU falls back to dense attention, which "
            f"cannot allocate S={seq} scores; set DEDLOC_BENCH_TINY=1 or "
            "DEDLOC_BENCH_SEQ<=4096 for a CPU smoke"
        )
    cfg = (AlbertConfig.tiny if tiny else AlbertConfig.large)(
        remat_policy="dots_no_batch_attn" if impl == "flash" else "dots_no_batch",
        attention_impl=impl,
        max_position_embeddings=seq,
    )
    max_pred = max_predictions_for(seq)
    model = AlbertForPreTraining(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((per_step, seq), jnp.int32)
    )["params"]

    def loss_fn(p, b, r):
        mlm, sop = model.apply({"params": p}, b["input_ids"],
                               b["attention_mask"],
                               mlm_positions=b["mlm_positions"])
        return albert_pretraining_loss_gathered(
            mlm, sop, b["mlm_label_ids"], b["mlm_weights"], b["sop_labels"])[0]

    host = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(host.integers(
            5, cfg.vocab_size, (per_step, seq)).astype(np.int32)),
        "attention_mask": jnp.ones((per_step, seq), jnp.int32),
        "mlm_positions": jnp.zeros((per_step, max_pred), jnp.int32),
        "mlm_label_ids": jnp.zeros((per_step, max_pred), jnp.int32),
        "mlm_weights": jnp.ones((per_step, max_pred), jnp.float32),
        "sop_labels": jnp.zeros((per_step,), jnp.int32),
    }
    grad = jax.jit(jax.grad(loss_fn))
    g = grad(params, batch, jax.random.PRNGKey(1))
    float(jax.tree.leaves(g)[0].ravel()[0])  # settle through the tunnel

    iters = 2 if tiny else 3
    best = float("inf")
    for block in range(3):
        start = time.perf_counter()
        for _ in range(iters):
            g = grad(params, batch, jax.random.PRNGKey(2))
        float(jax.tree.leaves(g)[0].ravel()[0])
        best = min(best, time.perf_counter() - start)
    tokens_per_sec = iters * per_step * seq / best
    result = {
        "metric": f"albert_large_longctx_s{seq}_fwdbwd_tokens_per_sec",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
    }
    if tiny:
        result["vs_baseline"] = 1.0  # CPU smoke: no meaningful anchor
    else:
        # the S=512 recipe sustains 99.45 samples/s x 512 tokens
        # (BASELINE.md round-3 headline); the ratio is the cost of 32x
        # longer context under O(S^2) attention FLOPs
        result["vs_baseline"] = round(tokens_per_sec / (99.45 * 512), 4)
    print(json.dumps(result))


# the 1,000-peer mixed acceptance scenario's wall on this box class BEFORE
# the virtual-time engine overhaul (timer wheel + sharded dispatch + lazy
# hydration + DHT lookup cache) — the sim_engine bench's vs_baseline anchor
# (SIMBENCH_r01.json records the pre/post pair)
_PRE_OVERHAUL_MIXED1000_WALL_S = 21.765


def run_sim_engine() -> None:
    """Virtual-time engine bench (DEDLOC_BENCH=sim_engine): the 1,000-peer
    mixed scenario at its DEFAULT spec — exactly what ``tools/swarm_sim.py
    --scenario mixed --peers 1000 --seed 0`` runs, so the trajectory stays
    comparable to the pre-overhaul measurement of the same command —
    end-to-end on the discrete-event engine: one core, zero real sleeping.
    The headline metric is timer events scheduled per wall second — the
    engine's dispatch throughput, which is exactly what the timer wheel /
    sharded dispatch / lazy hydration work moves. The event count is a
    deterministic function of (seed, spec), so events/sec isolates engine
    wall cost from workload drift, and it is higher-is-better as
    tools/bench_gate.py requires (wall seconds would gate backwards).
    vs_baseline is the pre-overhaul wall for this command on the same box
    class over this run's wall: the engine speedup. Unless
    DEDLOC_BENCH_TIMING=0, the record also carries the 10,000-peer diurnal
    point (the planet-scale proof: 10k peers over 24 virtual hours in well
    under a minute of wall).

    DEDLOC_BENCH_TINY=1 shrinks the roster for a CI smoke; the metric name
    carries the roster size so a smoke never gates against the full run.
    """
    import resource

    from dedloc_tpu.simulator import scenarios as S
    from dedloc_tpu.simulator.engine import SIM_EPOCH

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    timing = os.environ.get("DEDLOC_BENCH_TIMING", "1") != "0"
    peers = 100 if tiny else 1000
    spec = {"scenario": "mixed", "peers": peers, "seed": 0}
    run = S.ScenarioRun(spec)
    wall0 = time.perf_counter()
    with run.engine:
        run.engine.run(S.SCENARIOS["mixed"](run), timeout=36000.0)
        events = run.engine.clock.sleeper_stats()["scheduled_total"]
        virtual_s = run.engine.clock.offset - SIM_EPOCH
        run.engine.run(run.swarm.shutdown())
    run.engine.close()
    wall = time.perf_counter() - wall0

    result = {
        "metric": f"sim_mixed{peers}_timer_events_per_wall_sec",
        "value": round(events / wall, 1),
        "unit": "events/sec",
        "wall_s": round(wall, 3),
        "virtual_s": round(virtual_s, 3),
        "events_scheduled": events,
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1
        ),
        "vs_baseline": (
            1.0 if tiny  # smoke roster: no comparable pre-overhaul anchor
            else round(_PRE_OVERHAUL_MIXED1000_WALL_S / wall, 2)
        ),
    }
    if timing and not tiny:
        d = S.run_scenario({"scenario": "diurnal", "peers": 10000, "seed": 0})
        result["diurnal_10k"] = {
            "wall_s": d["wall_s"],
            "virtual_s": d["virtual_s"],
            "peak_online": d["diurnal"]["peak_online"],
            "get_success": d["diurnal"]["get_success"],
        }
    print(json.dumps(result))


def run_serving() -> None:
    """Serving-plane bench (DEDLOC_BENCH=serving): the ISSUE 20 acceptance
    scenario — a 1,000-peer fleet, 16 experts x 3 replicas, 8 gateways,
    a bursty 400-request trace with 6 expert hosts killed mid-trace — on
    the virtual-time engine. The headline is requests resolved per WALL
    second (higher-is-better, as tools/bench_gate.py requires): the
    request count is fixed by the spec, so the metric isolates the
    serving plane's Python cost (discovery parse, candidate ranking,
    hedged dispatch, telemetry) from workload drift. p99 latency and the
    fall-through rate ride along as SLO context — p99 is VIRTUAL time
    (the simulated fleet's latency), wall is the box's cost to simulate
    it.

    DEDLOC_BENCH_TINY=1 shrinks the fleet for a CI smoke; the metric name
    carries the roster size so a smoke never gates against the full run.
    """
    import resource

    from dedloc_tpu.simulator import scenarios as S

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    peers = 40 if tiny else 1000
    spec = {
        "scenario": "serving", "peers": peers, "seed": 0,
        "experts": 4 if tiny else 16,
        "hosts_per_expert": 2 if tiny else 3,
        "gateways": 2 if tiny else 8,
        "requests": 40 if tiny else 400,
        "burst": 4 if tiny else 8,
        "tokens": 16, "hidden": 8,
        "kill_hosts": 0 if tiny else 6, "kill_at_frac": 0.5,
    }
    wall0 = time.perf_counter()
    report = S.run_scenario(spec)
    wall = time.perf_counter() - wall0
    serving = report["serving"]
    print(json.dumps({
        "metric": f"serving{peers}_requests_per_wall_sec",
        "value": round(serving["completed"] / wall, 1),
        "unit": "requests/sec",
        "wall_s": round(wall, 3),
        "virtual_s": report["virtual_s"],
        "requests": serving["requests"],
        "served": serving["served"],
        "wedged": serving["wedged"],
        "fall_through_rate": serving["fall_through_rate"],
        "latency_p50_s": serving["latency_p50_s"],
        "latency_p99_s": serving["latency_p99_s"],
        "load_skew": serving["load_skew"],
        "peak_rss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1
        ),
    }))


def main() -> None:
    if os.environ.get("DEDLOC_BENCH") == "codec":
        run_codec()
        return
    if os.environ.get("DEDLOC_BENCH") == "allreduce_pipeline":
        run_allreduce_pipeline()
        return
    if os.environ.get("DEDLOC_BENCH") == "grad_pipeline":
        run_grad_pipeline()
        return
    if os.environ.get("DEDLOC_BENCH") == "checkpoint_restore":
        run_checkpoint_restore()
        return
    if os.environ.get("DEDLOC_BENCH") == "swav":
        run_swav()
        return
    if os.environ.get("DEDLOC_BENCH") == "longctx":
        run_longctx()
        return
    if os.environ.get("DEDLOC_BENCH") == "sim_engine":
        run_sim_engine()
        return
    if os.environ.get("DEDLOC_BENCH") == "serving":
        run_serving()
        return
    from dedloc_tpu.models.albert import (
        AlbertConfig,
        AlbertForPreTraining,
        albert_pretraining_loss_gathered,
    )
    from dedloc_tpu.optim import lamb
    from dedloc_tpu.parallel.train_step import TrainState, make_local_train_step

    tiny = os.environ.get("DEDLOC_BENCH_TINY", "") == "1"
    # the Pallas flash kernel beats XLA's dense attention on the full remat'd
    # train step (~86 vs ~77 samples/s on a v5e, measured 2026-07); off-TPU
    # it would run in interpret mode, so CI smoke keeps the dense path
    impl = "flash" if jax.default_backend() == "tpu" else "dense"
    # measurement overrides (remat sweep for BASELINE.md). Round-3 recipe
    # change: default policy dots_no_batch -> dots_no_batch_attn and block
    # length 5 -> 10 iters (see BASELINE.md round-3 notes for both the old-
    # and new-methodology numbers so rounds stay comparable).
    # Round-4 recipe: fused add+LN Pallas kernel + the fused_ln remat policy,
    # micro-batch 12 (the B sweep's sweet spot — small enough that XLA stops
    # inserting remat-compression copies, large enough to feed the MXU;
    # 8/10/14/16/24/32 all measured slower, BASELINE.md round-4 notes) and
    # 16 accumulation micro-batches per jitted step: the ~10 ms of per-step
    # plumbing (donated-state shuffling + LAMB apply) amortizes over 8x the
    # samples vs accum 2 (108.4 -> 112.3 samples/s; accum 32 adds only +0.4
    # more). Production-honest: one optimizer step at target_batch_size 4096
    # accumulates far more than 16 micro-batches per chip.
    remat = os.environ.get("DEDLOC_BENCH_REMAT", "fused_ln")
    from dedloc_tpu.models.albert import fused_ln_for_policy

    fused_ln = fused_ln_for_policy(remat)
    per_step_env = int(os.environ.get("DEDLOC_BENCH_BATCH", "0"))
    # flash-kernel tile sweep knob (perf probes; 512 is the shipped recipe)
    attn_block = int(os.environ.get("DEDLOC_BENCH_ATTN_BLOCK", "512"))
    if tiny:  # CI smoke on CPU
        cfg = AlbertConfig.tiny(remat_policy=remat, attention_impl=impl,
                                fused_ln=fused_ln)
        accum, per_step, seq, iters = 2, 4, 64, 3
    else:
        cfg = AlbertConfig.large(remat_policy=remat, attention_impl=impl,
                                 fused_ln=fused_ln,
                                 attention_block_size=attn_block)
        # iters per block: one scalar readback (~90 ms tunnel RTT) per block,
        # so longer blocks report closer to the true device rate
        accum, per_step, seq, iters = 16, 12, 512, 10
    if per_step_env:
        per_step = per_step_env
    accum_env = int(os.environ.get("DEDLOC_BENCH_ACCUM", "0"))
    if accum_env:
        accum = accum_env
    # gathered masked-position MLM head: vocab projection only where labels
    # exist (~15% of positions) — the TPU-native layout
    from dedloc_tpu.data.mlm import max_predictions_for

    max_pred = max_predictions_for(seq)

    model = AlbertForPreTraining(cfg)
    rng = jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros((per_step, seq), jnp.int32))["params"]
    tx = lamb(learning_rate=1.76e-3, weight_decay=0.01)
    state = jax.jit(lambda p: TrainState.create(p, tx))(params)

    def loss_fn(params, batch, rng):
        mlm_logits, sop_logits = model.apply(
            {"params": params},
            batch["input_ids"],
            batch["attention_mask"],
            mlm_positions=batch["mlm_positions"],
        )
        return albert_pretraining_loss_gathered(
            mlm_logits,
            sop_logits,
            batch["mlm_label_ids"],
            batch["mlm_weights"],
            batch["sop_labels"],
        )

    host = np.random.default_rng(0)
    ids = host.integers(5, cfg.vocab_size, (accum, per_step, seq)).astype(np.int32)
    labelled = host.random((accum, per_step, seq)) < 0.15
    labelled &= np.cumsum(labelled, axis=2) <= max_pred
    positions = np.zeros((accum, per_step, max_pred), np.int32)
    label_ids = np.zeros((accum, per_step, max_pred), np.int32)
    weights = np.zeros((accum, per_step, max_pred), np.float32)
    for a in range(accum):
        for i in range(per_step):
            idx = np.flatnonzero(labelled[a, i])
            positions[a, i, : len(idx)] = idx
            label_ids[a, i, : len(idx)] = ids[a, i, idx]
            weights[a, i, : len(idx)] = 1.0
    batch = {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.ones((accum, per_step, seq), jnp.int32),
        "mlm_positions": jnp.asarray(positions),
        "mlm_label_ids": jnp.asarray(label_ids),
        "mlm_weights": jnp.asarray(weights),
        "sop_labels": jnp.asarray(host.integers(0, 2, (accum, per_step)), jnp.int32),
    }

    train_step = make_local_train_step(loss_fn, tx, grad_accum_steps=accum)

    # Warmup: compile + one executed step (scalar readback forces completion —
    # block_until_ready alone does not sync through the axon tunnel).
    state, metrics = train_step(state, batch, jax.random.PRNGKey(1))
    float(metrics["loss"])

    # Steady-state throughput: steps chain on-device (donated state), one
    # scalar readback per BLOCK; best-of-blocks guards against the tunnel's
    # run-to-run timing noise.
    best = float("inf")
    for block in range(3):
        start = time.perf_counter()
        for i in range(iters):
            state, metrics = train_step(
                state, batch, jax.random.PRNGKey(2 + block * iters + i)
            )
        float(metrics["loss"])
        best = min(best, time.perf_counter() - start)

    samples_per_sec = iters * accum * per_step / best
    result = {
        "metric": "albert_large_train_samples_per_sec_per_chip",
        "value": round(samples_per_sec, 3),
        "unit": "samples/sec",
        "vs_baseline": round(samples_per_sec / T4_BASELINE_SAMPLES_PER_SEC, 3),
    }
    peak = chip_peak_tflops()
    if peak and not tiny:
        flops = albert_train_flops_per_sample(cfg, seq, max_pred)
        result["mfu"] = round(samples_per_sec * flops / (peak * 1e12), 4)
        result["model_tflops_per_sample"] = round(flops / 1e12, 4)
        result["chip"] = jax.devices()[0].device_kind
    print(json.dumps(result))


if __name__ == "__main__":
    main()
